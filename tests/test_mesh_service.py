"""Mesh-sharded hash service drills (parallel/mesh.py + ops/hash_service.py
mesh integration + ops/supervisor.py DeviceBreakerBoard).

The acceptance drills, all on the virtual 8-device CPU mesh (conftest):

- randomized differential sweep: the mesh-sharded committers
  (FusedMeshEngine under TurboCommitter/TrieCommitter) produce roots and
  branch nodes bit-identical to the single-device/numpy committers,
  including non-power-of-two meshes whose tier ladders leave the pow2
  grid (uneven tiers — the satellite clamp fix);
- sub-mesh rebuild lease: a pipelined rebuild claims k of n devices
  while live-lane dispatches KEEP COMPLETING on the remaining devices
  (no pause, no CPU bypass), roots bit-identical;
- per-device breaker drill: one injected device wedge
  (FaultInjector.device_wedge / RETH_TPU_FAULT_DEVICE_WEDGE) sheds that
  device, the in-flight batch REPLAYS on the shrunken mesh with
  bit-identical digests, and the numpy-twin replay only fires once
  every device has tripped (the final rung).
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from reth_tpu.metrics import MetricsRegistry
from reth_tpu.ops.fused_commit import FusedLevelEngine, FusedMeshEngine
from reth_tpu.ops.hash_service import HashService
from reth_tpu.ops.supervisor import (
    DeviceBreakerBoard,
    FaultInjector,
    InjectedDeviceWedge,
)
from reth_tpu.parallel.mesh import (
    DEFAULT_PARTITION_RULES,
    HashMesh,
    MeshKeccak,
    match_partition_rule,
    mesh_tier,
)
from reth_tpu.primitives.keccak import keccak256, keccak256_batch_np
from reth_tpu.primitives.rlp import rlp_encode


def _mesh(n: int = 8) -> HashMesh:
    import jax

    return HashMesh(jax.devices()[:n], registry=MetricsRegistry())


def _svc(hm: HashMesh, **kw) -> HashService:
    kw.setdefault("backend", keccak256_batch_np)
    kw.setdefault("registry", MetricsRegistry())
    kw.setdefault("min_tier", 16)
    return HashService(mesh=hm, **kw)


def _msgs(seed: int, n: int, lo: int = 1, hi: int = 300) -> list[bytes]:
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 256, size=int(rng.integers(lo, hi)),
                         dtype=np.uint8).tobytes() for _ in range(n)]


def _job(n: int, seed: int):
    r = np.random.default_rng(seed)
    keys = r.integers(0, 256, (n, 32), dtype=np.uint8)
    vals = [rlp_encode(bytes(r.integers(0, 256, size=int(r.integers(1, 60)),
                                        dtype=np.uint8))) for _ in range(n)]
    return keys, vals


# -- partition-rule table ------------------------------------------------------


def test_partition_rule_table_decisions():
    # fused rebuild windows always shard; scalars never do; coalesced
    # keccak batches shard once every device gets a real shard
    assert match_partition_rule(DEFAULT_PARTITION_RULES,
                                "rebuild/fused.packed", 8, 8) == "batch"
    assert match_partition_rule(DEFAULT_PARTITION_RULES,
                                "live/keccak.scalar", 1, 8) == "single"
    assert match_partition_rule(DEFAULT_PARTITION_RULES,
                                "live/keccak.masked", 1024, 8) == "batch"
    assert match_partition_rule(DEFAULT_PARTITION_RULES,
                                "proof/keccak.masked", 8, 8) == "single"
    assert match_partition_rule(DEFAULT_PARTITION_RULES,
                                "live/keccak.masked", 1024, 1) == "single"


def test_spec_for_shards_large_keeps_scalar_single():
    hm = _mesh(8)
    spec, mesh = hm.spec_for("live", "keccak.masked", 2048)
    assert len(spec) == 1 and mesh.devices.size == 8
    spec, mesh = hm.spec_for("proof", "keccak.scalar", 1)
    assert len(spec) == 0 and mesh.devices.size == 1
    # every device dead -> (None, None): the caller takes the CPU rung
    for i in range(8):
        hm.mark_unhealthy(i)
    assert hm.spec_for("live", "keccak.masked", 2048) == (None, None)


# -- tier ladder / satellite clamp fix ----------------------------------------


def test_mesh_tier_divisible_and_clamped():
    # rounded floor, x2 growth, divisibility by the device count
    assert mesh_tier(100, 1024, 6) == 1026
    assert mesh_tier(2000, 1024, 6) == 2052
    assert mesh_tier(100, 1024, 8) == 1024
    # the clamp lands ON the ladder, never at the raw ceiling
    assert mesh_tier(70000, 1024, 6, 65536) == 32832
    assert mesh_tier(70000, 1024, 8, 65536) == 65536
    for mult in (2, 3, 5, 6, 7, 8):
        t = mesh_tier(12345, 1024, mult, 65536)
        assert t % mult == 0 and t <= 65536


def test_fused_mesh_row_cap_stays_on_ladder():
    """The satellite fix: the row-range split threshold is the largest
    LADDER tier under the ceilings, so a chunk split can never mint a
    tier above MAX_BATCH_ROWS or off the device-count-multiple grid
    (6 devices: 1026 -> 4104 -> 16416; the old raw-ceiling cap of 65536
    would have minted 65664 > MAX_BATCH_ROWS)."""
    import jax
    from jax.sharding import Mesh

    mesh6 = Mesh(np.array(jax.devices()[:6]), ("data",))
    eng = FusedMeshEngine(mesh6, min_tier=1024)
    assert eng.min_tier == 1026
    cap = eng._row_cap()
    assert cap == 16416  # 1026 * 4 * 4: the next rung (65664) > 65536
    assert cap % 6 == 0 and cap <= eng.MAX_BATCH_ROWS
    # the guard itself: an off-ladder tier is an assertion, not silence
    with pytest.raises(AssertionError):
        eng._check_batch_tier(1028)
    # single-device engines keep the old pow2 cap exactly
    assert FusedLevelEngine(min_tier=1024)._row_cap() == 65536


def test_row_range_split_parity_on_shrunk_ceiling():
    """dispatch_packed across a row-range split (rows > row cap) on a
    6-device mesh with a shrunken MAX_BATCH_ROWS: every minted tier obeys
    the clamp (asserted inside the engine) and digests stay bit-identical
    to the reference keccak."""
    import jax
    from jax.sharding import Mesh

    mesh6 = Mesh(np.array(jax.devices()[:6]), ("data",))
    eng = FusedMeshEngine(mesh6, min_tier=18)
    eng.MAX_BATCH_ROWS = 100  # ladder: 18 -> 72; cap 72 < 100
    assert eng._row_cap() == 72
    rng = np.random.default_rng(9)
    rows = [rng.integers(0, 256, size=int(rng.integers(1, 120)),
                         dtype=np.uint8).tobytes() for _ in range(150)]
    eng.begin(len(rows) + 1)
    slots = np.array([eng.alloc_slot() for _ in rows], dtype=np.int32)
    flat = np.frombuffer(b"".join(rows), dtype=np.uint8)
    row_len = np.array([len(r) for r in rows], dtype=np.uint32)
    row_off = (np.cumsum(row_len) - row_len).astype(np.uint32)
    eng.dispatch_packed(flat, row_off, row_len, slots, None, b_tier=1)
    digests = eng.finish()
    for s, r in zip(slots, rows):
        assert digests[s].tobytes() == keccak256(r)


# -- randomized differential sweep (mesh vs single-device) --------------------


def _differential(n_dev: int, min_tier: int, seeds) -> None:
    import jax
    from jax.sharding import Mesh

    from reth_tpu.trie.turbo import TurboCommitter

    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("data",))
    dev = TurboCommitter(backend="device", min_tier=min_tier, mesh=mesh)
    cpu = TurboCommitter(backend="numpy")
    for seed in seeds:
        jobs = [_job(int(n), seed * 10 + i)
                for i, n in enumerate((130, 50, 9, 1))]
        got = dev.commit_hashed_many(jobs, collect_branches=True)
        want = cpu.commit_hashed_many(jobs, collect_branches=True)
        assert [r.root for r in got] == [r.root for r in want]
        assert [r.branch_nodes for r in got] == [r.branch_nodes for r in want]
        # pipelined path (the rebuild's shape) too
        got_p = dev.commit_hashed_pipelined(jobs)
        assert [r.root for r in got_p] == [r.root for r in want]


@pytest.mark.slow
def test_turbo_mesh_randomized_differential():
    """The production level loop (packed + branch dispatches) sharded over
    the full 8-device mesh vs the numpy committer: roots and TrieUpdates
    branch nodes bit-identical across randomized job mixes. (Tier-1
    already pins single-shot mesh parity via test_fused_commit /
    test_turbo_commit; this randomized sweep rides make test-mesh.)"""
    _differential(8, 16, seeds=(1,))


@pytest.mark.slow
def test_turbo_mesh_differential_uneven_meshes():
    """Extended sweep (make test-mesh): non-power-of-two meshes whose tier
    ladders leave the pow2 grid, plus extra randomized seeds."""
    _differential(8, 16, seeds=(2,))
    _differential(6, 20, seeds=(1, 2))
    _differential(3, 8, seeds=(1, 2))


@pytest.mark.slow
def test_trie_committer_fused_mesh_accepts_hashmesh():
    """TrieCommitter's fused path (template/splice dispatches) over a
    HashMesh descriptor — FusedMeshEngine snapshots the live sub-mesh."""
    from reth_tpu.trie.committer import TrieCommitter

    hm = _mesh(8)
    hm.mark_unhealthy(7)  # engine must form over the 7 live devices
    sharded = TrieCommitter(fused=True, min_tier=14, mesh=hm)
    baseline = TrieCommitter(hasher=keccak256_batch_np)
    rng = np.random.default_rng(4)
    leaves = [(bytes(rng.integers(0, 16, 64, dtype=np.uint8)),
               rlp_encode(bytes(rng.integers(0, 256, 40, dtype=np.uint8))))
              for _ in range(120)]
    got = sharded.commit(leaves)
    want = baseline.commit(leaves)
    assert got.root == want.root
    assert got.branch_nodes == want.branch_nodes


# -- mesh-sharded service ------------------------------------------------------


def test_service_mesh_sharded_parity_and_routing():
    hm = _mesh(8)
    svc = _svc(hm)
    try:
        big = _msgs(1, 120)
        assert svc.client("live")(big) == [keccak256(m) for m in big]
        assert svc.client("proof")([b"k"]) == [keccak256(b"k")]
        assert svc.mesh_sharded >= 1 and svc.mesh_single >= 1
        snap = svc.snapshot()["mesh"]
        assert snap["total"] == 8 and snap["healthy"] == 8
    finally:
        svc.stop()


def test_service_mesh_streaming_chunks_fuse():
    """map_chunks streaming (the parallel sparse commit's encode-pool
    shape) over the meshed service: digests in order, bit-identical."""
    hm = _mesh(8)
    svc = _svc(hm, window_s=0.01)
    try:
        msgs = _msgs(2, 96)
        chunks = [msgs[i:i + 8] for i in range(0, len(msgs), 8)]
        out = svc.client("live").map_chunks(chunks)
        assert out == [keccak256(m) for m in msgs]
    finally:
        svc.stop()


def test_submesh_lease_live_lane_continues():
    """Acceptance drill: a rebuild holds k=4 of 8 devices; live-lane
    dispatches complete ON THE REMAINING DEVICES while the lease is held
    — verified by joining the live worker inside the lease — with zero
    CPU lease-bypasses and correct digests."""
    hm = _mesh(8)
    svc = _svc(hm)
    try:
        msgs = _msgs(3, 128)
        want = [keccak256(m) for m in msgs]
        results = []

        def live_worker():
            for _ in range(4):
                results.append(svc.client("live")(msgs) == want)

        with svc.lease(what="rebuild", devices=4):
            assert svc.rebuild_mesh().devices.size == 4
            assert svc.snapshot()["mesh"]["leased"] == 4
            t = threading.Thread(target=live_worker)
            t.start()
            t.join(60)
            assert not t.is_alive()
        assert results == [True] * 4
        assert svc.lease_bypasses == 0 and svc.submesh_leases == 1
        assert svc.snapshot()["mesh"]["leased"] == 0  # released
    finally:
        svc.stop()


def _turbo_lease_drill(commit) -> None:
    """Shared body: a turbo commit through a meshed hash service takes the
    sub-mesh lease (engine sharded over the leased k devices) while a
    live-lane client keeps hashing — roots bit-identical to numpy, no CPU
    bypasses."""
    from reth_tpu.trie.turbo import TurboCommitter

    hm = _mesh(8)
    svc = _svc(hm)
    try:
        jobs = [_job(120, 2), _job(60, 3)]
        # one batch tier for every level (min_tier pads them all to 256):
        # the drill is about the LEASE, not tier variety — tier sweeps
        # live in the differential tests, so keep the compile count here
        # at one program per (kind, topology)
        dev = TurboCommitter(backend="device", min_tier=256,
                             hash_service=svc)
        cpu = TurboCommitter(backend="numpy")
        stop = threading.Event()
        ok: list[bool] = []
        msgs = _msgs(5, 48)
        want = [keccak256(m) for m in msgs]

        def live():
            while not stop.is_set():
                ok.append(svc.client("live")(msgs) == want)

        t = threading.Thread(target=live)
        t.start()
        try:
            got = commit(dev, jobs)
        finally:
            stop.set()
            t.join(30)
        want_roots = [r.root for r in commit(cpu, jobs)]
        assert [r.root for r in got] == want_roots
        assert svc.submesh_leases == 1 and svc.lease_bypasses == 0
        assert ok and all(ok)
        assert svc.snapshot()["mesh"]["leased"] == 0
    finally:
        svc.stop()


@pytest.mark.slow
def test_turbo_commit_submesh_lease_roots_and_live_traffic():
    """(make test-mesh: mesh-program compile cost keeps this out of the
    tier-1 budget; the lease semantics themselves are pinned fast by
    test_submesh_lease_live_lane_continues above.)"""
    _turbo_lease_drill(lambda c, jobs: c.commit_hashed_many(jobs))


@pytest.mark.slow
def test_turbo_pipelined_rebuild_submesh_lease():
    """Extended (make test-mesh): the overlapped RebuildPipeline variant —
    many packed windows stream through the leased sub-mesh engine."""
    _turbo_lease_drill(lambda c, jobs: c.commit_hashed_pipelined(jobs))


# -- per-device breaker degradation -------------------------------------------


def test_device_wedge_shrinks_mesh_and_replays_batch():
    """Acceptance drill: one injected device wedge sheds that device and
    the in-flight batch replays on the 7 survivors — digests
    bit-identical, every future completes exactly once, and the CPU twin
    is NOT involved."""
    hm = _mesh(8)
    svc = _svc(hm,
               breaker_board=DeviceBreakerBoard(hm, failure_threshold=1),
               device_injector=FaultInjector(device_wedge=(3,)))
    try:
        msgs = _msgs(6, 100)
        fut = svc.submit("live", msgs)
        assert fut.result(60) == [keccak256(m) for m in msgs]
        assert fut.completions == 1
        snap = svc.snapshot()["mesh"]
        assert snap["healthy"] == 7 and snap["unhealthy"] == 1
        assert snap["mesh_replays"] == 1
        assert svc.replays == 0  # the final rung never fired
        # subsequent dispatches run on the shrunken mesh without replay
        assert svc.client("payload")(msgs) == [keccak256(m) for m in msgs]
        assert svc.mesh_replays == 1
    finally:
        svc.stop()


def test_all_devices_trip_then_cpu_final_rung():
    """Wedging every device walks the whole ladder: shrink, shrink, ...,
    exhausted -> the numpy-twin replay completes the batch (the FINAL
    rung, exactly once) with correct digests."""
    hm = _mesh(4)
    svc = _svc(hm,
               breaker_board=DeviceBreakerBoard(hm, failure_threshold=1),
               device_injector=FaultInjector(device_wedge=(0, 1, 2, 3)))
    try:
        msgs = _msgs(7, 60)
        assert svc.client("live")(msgs) == [keccak256(m) for m in msgs]
        snap = svc.snapshot()["mesh"]
        assert snap["healthy"] == 0 and snap["unhealthy"] == 4
        assert svc.replays == 1  # CPU twin, once
        assert svc.breaker_board.exhausted()
    finally:
        svc.stop()


def test_breaker_cooldown_readmits_device():
    """Trial-by-fire recovery: a shed device rejoins once its breaker
    cooldown elapses (poll -> HALF_OPEN), and a clean dispatch closes the
    breaker for good."""
    clock = [0.0]
    hm = _mesh(8)
    board = DeviceBreakerBoard(hm, failure_threshold=1, reset_timeout=10.0,
                               clock=lambda: clock[0])
    board.record_failure(2, attributed=True)
    assert not hm.is_healthy(2)
    assert board.poll() == 0  # cooldown not elapsed
    clock[0] = 11.0
    assert board.poll() == 1
    assert hm.is_healthy(2)
    board.record_success((2,))
    assert board.breakers[2].state == "closed"


def test_unattributed_failures_need_threshold():
    hm = _mesh(8)
    board = DeviceBreakerBoard(hm, failure_threshold=2)
    assert not board.record_failure(5)
    assert hm.is_healthy(5)
    assert board.record_failure(5)  # second strike sheds it
    assert not hm.is_healthy(5)


def test_device_wedge_injector_from_env(monkeypatch):
    monkeypatch.setenv("RETH_TPU_FAULT_DEVICE_WEDGE", "1,5")
    inj = FaultInjector.from_env()
    assert inj is not None and inj.device_wedge == frozenset((1, 5))
    with pytest.raises(InjectedDeviceWedge) as ei:
        inj.on_mesh_dispatch((0, 1, 2))
    assert ei.value.device_index == 1
    inj.on_mesh_dispatch((0, 2, 3))  # no wedged device participates


# -- warm-up integration -------------------------------------------------------


@pytest.mark.slow
def test_warmup_builds_mesh_shapes_and_routes():
    """Real sharded AOT builds over the 8-device mesh: the SPMD menu
    variants compile to WARM, route_bucket answers per mesh size, and the
    compile cache key carries the mesh size."""
    from reth_tpu.ops.warmup import CompileCache, MenuShape, WarmupManager

    menu = [MenuShape("keccak.masked", 4, 16, 8),
            MenuShape("fused.plain", 4, 16, 8),
            MenuShape("fused.splice", 4, 16, 8)]
    mgr = WarmupManager(menu=menu, registry=MetricsRegistry(), budget=120,
                        attempts=1, verify_cache=False, enable_cache=False)
    snap = mgr.run()
    assert snap["state"] == "warm" and snap["warm"] == 3
    assert mgr.route_bucket("keccak.masked", 4, 16, 8)
    assert "keccak.masked:4x16@m8" in snap["shapes"]


def test_compile_cache_key_gains_mesh_size(tmp_path):
    from reth_tpu.ops.warmup import CompileCache

    single = CompileCache(tmp_path, sources=[])
    meshed = CompileCache(tmp_path, sources=[], mesh_size=8)
    assert single.dir != meshed.dir
    assert meshed.dir.name.endswith("-m8")


@pytest.mark.slow
def test_bench_mesh_mode_end_to_end(tmp_path):
    """RETH_TPU_BENCH_MODE=mesh at test size: one JSON line with
    per-mesh-size throughput + compile wall, roots verified identical,
    n_devices + mesh_degraded fields present (the bench_daemon contract),
    rc=0."""
    import json
    import os
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env.update(JAX_PLATFORMS="cpu",
               RETH_TPU_BENCH_MODE="mesh",
               RETH_TPU_BENCH_MESH_DEVICES="1,2",
               RETH_TPU_BENCH_MESH_ACCOUNTS="800",
               RETH_TPU_BENCH_MESH_SLOTS="300",
               RETH_TPU_BENCH_MESH_TIER="256",
               RETH_TPU_BENCH_TIMEOUT="240",
               RETH_TPU_BENCH_BASELINE_STORE=str(tmp_path / "store.json"))
    r = subprocess.run([sys.executable, str(repo / "bench.py")],
                       capture_output=True, text=True, timeout=300, env=env,
                       cwd=repo)
    assert r.returncode == 0, r.stderr[-500:]
    line = json.loads(r.stdout.strip().splitlines()[-1])
    assert line["metric"] == "mesh_rebuild_hashes_per_sec"
    assert line["value"] > 0 and "error" not in line
    assert line["roots_identical"] is True
    assert line["n_devices"] == 2 and line["mesh_degraded"] == 0
    per = line["per_mesh"]
    assert set(per) == {"1", "2"}
    for stats in per.values():
        assert stats["hashes_per_sec"] > 0
        assert stats["compile_wall_s"] >= 0


def test_mesh_keccak_unwarm_shape_routes_to_cpu():
    """Degraded-mode serving holds on the mesh path too: an un-warm
    (program, block, batch, mesh) shape hashes on the CPU twin with
    bit-identical digests — never a fresh compile mid-commit."""
    from reth_tpu.ops.warmup import MenuShape, WarmupManager

    hm = _mesh(8)
    mgr = WarmupManager(menu=[MenuShape("keccak.masked", 4, 16, 8)],
                        registry=MetricsRegistry(), builder=lambda s: None,
                        verify_cache=False, enable_cache=False)
    mgr._active = True  # mid-warm-up, nothing compiled
    mk = MeshKeccak(hm, min_tier=16, block_tier=4, warmup=mgr)
    msgs = _msgs(8, 40)
    mesh, _ = hm.live_snapshot()
    assert mk.hash_sharded(msgs, mesh) == [keccak256(m) for m in msgs]
    assert mgr.cpu_routed > 0
