"""Storage-V2 split layout: routing, persistence, invariants, history RPC.

Reference analogue: the RocksDB storage-v2 provider + invariants
(crates/storage/provider/src/providers/rocksdb/provider.rs:28-40,
invariants.rs) — history/lookup tables on a dedicated second store, the
layout persisted per datadir, and startup consistency checks that heal
an aux store left AHEAD of the checkpoints (the crash direction the
aux-first commit order produces) or demand an unwind when it is behind.
"""

from __future__ import annotations

import pytest

from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.storage import ProviderFactory, open_database
from reth_tpu.storage.kv import MemDb
from reth_tpu.storage.settings import (
    SplitDb,
    StorageSettings,
    V2_TABLES,
    check_consistency,
    read_settings,
)
from reth_tpu.storage.tables import Tables, be64
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)


def _synced_factory(db, n_blocks=4):
    from reth_tpu.consensus import EthBeaconConsensus
    from reth_tpu.stages import Pipeline, default_stages
    from reth_tpu.storage.genesis import import_chain, init_genesis

    from reth_tpu.primitives.keccak import keccak256

    store = bytes.fromhex("5f355f5500")  # sstore(0, calldata[0])
    caddr = b"\x5a" * 20
    alice = Wallet(0xA11CE)
    builder = ChainBuilder(
        {alice.address: Account(balance=10**21),
         caddr: Account(code_hash=keccak256(store))},
        codes={keccak256(store): store}, committer=CPU)
    for i in range(n_blocks):
        builder.build_block([
            alice.transfer(b"\x0b" * 20, 100 + i),
            alice.call(caddr, (i + 1).to_bytes(32, "big")),
        ])
    factory = ProviderFactory(db)
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis,
                 codes=builder.codes_at_genesis, committer=CPU)
    import_chain(factory, builder.blocks[1:], EthBeaconConsensus(CPU))
    Pipeline(factory, default_stages(committer=CPU)).run(n_blocks)
    return factory, builder, alice


def test_split_routing_and_both_layout_history_rpc(tmp_path):
    """The same sync lands v2 tables in the AUX store under the split
    layout, and historical state reads agree between layouts."""
    v1 = ProviderFactory(MemDb())
    f1, b1, _ = _synced_factory(v1.db)
    split = SplitDb(MemDb(), MemDb())
    f2, b2, _ = _synced_factory(split)
    # routing: v2 tables live ONLY in the aux store
    with split.aux.tx() as aux_tx, split.main.tx() as main_tx:
        for t in V2_TABLES:
            assert aux_tx.entry_count(t) > 0, t
            assert main_tx.entry_count(t) == 0, t
        assert main_tx.entry_count(Tables.Headers.name) > 0
        assert aux_tx.entry_count(Tables.Headers.name) == 0
    # history reads agree across layouts at every height
    from reth_tpu.storage.historical import HistoricalStateProvider

    target = b"\x0b" * 20
    for n in range(0, 4):
        with f1.provider() as p1, f2.provider() as p2:
            h1 = HistoricalStateProvider(p1, n).account(target)
            h2 = HistoricalStateProvider(p2, n).account(target)
            assert h1 == h2, n
    # tx-hash lookup served from the aux store
    tx_hash = b1.blocks[1].transactions[0].hash
    with f2.provider() as p:
        assert p.tx.get(Tables.TransactionHashNumbers.name, tx_hash) is not None


def test_settings_persist_per_datadir(tmp_path):
    db = open_database("memdb", tmp_path, storage_v2=True)
    assert isinstance(db, SplitDb)
    assert read_settings(db.main) == StorageSettings(storage_v2=True)
    db.flush()
    # reopen WITHOUT the flag: the datadir's recorded layout wins
    db2 = open_database("memdb", tmp_path)
    assert isinstance(db2, SplitDb)
    # an INITIALISED v1 datadir refuses a later --storage.v2 (its history
    # lives in the main store; a silent upgrade would orphan it)
    other = tmp_path / "other"
    other.mkdir()
    dbv1 = open_database("memdb", other)
    assert not isinstance(dbv1, SplitDb)
    tx = dbv1.tx_mut()
    tx.put(Tables.CanonicalHeaders.name, be64(0), b"\x00" * 32)
    tx.commit()
    dbv1.flush()
    with pytest.raises(ValueError, match="v1 layout"):
        open_database("memdb", other, storage_v2=True)
    # but reopening WITHOUT the flag keeps working
    assert not isinstance(open_database("memdb", other), SplitDb)


def test_clean_restart_skips_heavy_checks_and_keeps_rows():
    """A clean (or plain mid-sync) restart must NOT touch legitimate aux
    rows: lookup entries are written at body-insert time and may sit far
    beyond the TransactionLookup checkpoint without any anomaly."""
    split = SplitDb(MemDb(), MemDb())
    factory, builder, _ = _synced_factory(split)
    with factory.provider_rw() as p:
        # mid-sync shape: checkpoints behind, rows present (NORMAL)
        p.save_stage_checkpoint("TransactionLookup", 1)
    assert check_consistency(factory) is None
    with factory.provider() as p:
        for blk in builder.blocks[1:]:
            for tx in blk.transactions:
                assert p.tx.get(Tables.TransactionHashNumbers.name,
                                tx.hash) is not None


def test_invariants_heal_torn_commit():
    """A TORN commit (aux stamped one epoch ahead of main) triggers
    healing: orphaned lookup rows beyond the committed tx space are
    pruned, history shards touched by orphaned changesets refiltered,
    orphaned changesets dropped — and the epochs converge again."""
    from reth_tpu.storage.settings import _EPOCH_KEY, _read_epoch

    split = SplitDb(MemDb(), MemDb())
    factory, builder, _ = _synced_factory(split)
    tip = len(builder.blocks) - 1
    with factory.provider_rw() as p:
        # the crash shape: aux committed block tip+1's rows, main didn't.
        # Orphan lookup row (tx number beyond the committed space),
        # orphan history via a changeset above the exec checkpoint.
        idx = p.block_body_indices(tip)
        p.tx.put(Tables.TransactionHashNumbers.name, b"\xfa" * 32,
                 be64(idx.next_tx_num + 7))
        p.tx.put(Tables.AccountChangeSets.name, be64(tip + 1),
                 b"\x0b" * 20 + b"", dupsort=True)
        tail = be64((1 << 64) - 1)
        raw = p.tx.get(Tables.AccountsHistory.name, b"\x0b" * 20 + tail)
        p.tx.put(Tables.AccountsHistory.name, b"\x0b" * 20 + tail,
                 (raw or b"") + be64(tip + 1))
    # stamp the torn state AFTER the setup commit (which synced epochs)
    tx = split.aux.tx_mut()
    tx.put(Tables.Metadata.name, _EPOCH_KEY, be64(_read_epoch(split.aux) + 1))
    tx.commit()
    assert _read_epoch(split.aux) != _read_epoch(split.main)

    assert check_consistency(factory) is None
    with factory.provider() as p:
        assert p.tx.get(Tables.TransactionHashNumbers.name,
                        b"\xfa" * 32) is None
        assert not p.tx.get_dups(Tables.AccountChangeSets.name, be64(tip + 1))
        raw = p.tx.get(Tables.AccountsHistory.name,
                       b"\x0b" * 20 + be64((1 << 64) - 1))
        blocks = [int.from_bytes(raw[i:i + 8], "big")
                  for i in range(0, len(raw or b""), 8)]
        assert all(b <= tip for b in blocks), blocks
        # legitimate rows survived the heal
        for tx_ in builder.blocks[-1].transactions:
            assert p.tx.get(Tables.TransactionHashNumbers.name,
                            tx_.hash) is not None
    assert _read_epoch(split.aux) == _read_epoch(split.main)


def test_invariants_detect_aux_behind():
    """A lookup table missing checkpoint-range hashes yields an unwind
    target at the highest still-indexed block."""
    split = SplitDb(MemDb(), MemDb())
    factory, builder, _ = _synced_factory(split)
    # wipe the lookup entries for the LAST block only
    last_txs = builder.blocks[-1].transactions
    with factory.provider_rw() as p:
        for tx in last_txs:
            p.tx.delete(Tables.TransactionHashNumbers.name, tx.hash)
    target = check_consistency(factory)
    assert target == len(builder.blocks) - 2  # highest intact block


def test_node_startup_runs_invariants(tmp_path):
    """A Node opening a v2 datadir reconciles the aux store on launch."""
    from reth_tpu.node import Node, NodeConfig

    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)},
                           committer=CPU)
    cfg = NodeConfig(dev=True, datadir=tmp_path, db_backend="memdb",
                     storage_v2=True, genesis_header=builder.genesis,
                     genesis_alloc=builder.accounts_at_genesis)
    n = Node(cfg, committer=CPU)
    try:
        assert isinstance(n.factory.db, SplitDb)
        for _ in range(2):
            n.pool.add_transaction(alice.transfer(b"\x0c" * 20, 5))
            n.miner.mine_block()
        # history RPC path over the split layout
        with n.factory.provider() as p:
            assert p.tx.entry_count(Tables.AccountChangeSets.name) >= 0
    finally:
        n.stop()
