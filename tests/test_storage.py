"""Storage layer tests: KV semantics, dupsort cursors, provider round-trips."""

import numpy as np
import pytest

from reth_tpu.primitives.types import (
    Account,
    Block,
    Header,
    Receipt,
    Log,
    Transaction,
    Withdrawal,
)
from reth_tpu.storage import MemDb, ProviderFactory, Tables
from reth_tpu.storage.tables import be64
from reth_tpu.trie.committer import BranchNode


def _native_db():
    from reth_tpu.storage.native import NativeDb

    return NativeDb()


@pytest.fixture(params=["mem", "native", "paged"])
def make_db(request, tmp_path):
    """All storage backends must satisfy the same KV contract."""
    if request.param == "mem":
        return MemDb
    if request.param == "paged":
        from reth_tpu.storage.native import PagedDb

        try:
            PagedDb(tmp_path / "probe").close()
        except Exception as e:  # toolchain missing
            pytest.skip(f"paged backend unavailable: {e}")
        import itertools

        seq = itertools.count()
        return lambda: PagedDb(tmp_path / f"paged{next(seq)}")
    try:
        _native_db()
    except Exception as e:  # toolchain missing
        pytest.skip(f"native backend unavailable: {e}")
    return _native_db


def test_kv_basic_and_cursor_order_backends(make_db):
    db = make_db()
    with db.tx_mut() as tx:
        for k in (b"b", b"a", b"c"):
            tx.put("t", k, b"v" + k)
    tx = db.tx()
    cur = tx.cursor("t")
    assert [k for k, _ in cur.walk()] == [b"a", b"b", b"c"]
    assert cur.seek(b"aa") == (b"b", b"vb")
    assert cur.seek_exact(b"aa") is None
    assert cur.seek_exact(b"c") == (b"c", b"vc")
    assert cur.prev() == (b"b", b"vb")
    assert cur.last() == (b"c", b"vc")


def test_dupsort_backends(make_db):
    db = make_db()
    with db.tx_mut() as tx:
        tx.put("d", b"k1", b"bbb", dupsort=True)
        tx.put("d", b"k1", b"aaa", dupsort=True)
        tx.put("d", b"k1", b"ccc", dupsort=True)
        tx.put("d", b"k2", b"zzz", dupsort=True)
    cur = db.tx().cursor("d")
    assert list(cur.walk_dup(b"k1")) == [(b"k1", b"aaa"), (b"k1", b"bbb"), (b"k1", b"ccc")]
    assert cur.seek_by_key_subkey(b"k1", b"bb") == (b"k1", b"bbb")
    assert cur.seek_by_key_subkey(b"k1", b"zzz") is None
    assert [v for _, v in db.tx().cursor("d").walk()] == [b"aaa", b"bbb", b"ccc", b"zzz"]
    with db.tx_mut() as tx:
        assert tx.delete("d", b"k1", b"bbb")
    assert list(db.tx().cursor("d").walk_dup(b"k1")) == [(b"k1", b"aaa"), (b"k1", b"ccc")]


def test_cursor_failed_seek_semantics_backends(make_db):
    """Failed seeks leave the cursor past-the-end on BOTH backends:
    next() -> None, prev() -> last entry (MemDb _ki==len semantics)."""
    db = make_db()
    with db.tx_mut() as tx:
        for k in (b"a", b"b", b"c"):
            tx.put("t", k, b"v" + k)
    cur = db.tx().cursor("t")
    assert cur.seek(b"zzz") is None
    assert cur.next() is None
    assert cur.prev() == (b"c", b"vc")
    cur2 = db.tx().cursor("t")
    assert cur2.seek_exact(b"nope") is None
    assert cur2.next() is None
    # fresh cursor: next() == first()
    cur3 = db.tx().cursor("t")
    assert cur3.next() == (b"a", b"va")


def test_abort_backends(make_db):
    db = make_db()
    with db.tx_mut() as tx:
        tx.put("t", b"k", b"v1")
    tx = db.tx_mut()
    tx.put("t", b"k", b"v2")
    tx.put("t", b"k2", b"x")
    tx.delete("t", b"k")
    tx.clear("t")
    tx.put("t", b"k3", b"z")
    tx.abort()
    assert db.tx().get("t", b"k") == b"v1"
    assert db.tx().get("t", b"k2") is None
    assert db.tx().get("t", b"k3") is None


def test_provider_over_both_backends(make_db):
    factory = ProviderFactory(make_db())
    addr = b"\x0a" * 20
    with factory.provider_rw() as p:
        p.put_account(addr, Account(nonce=1, balance=100))
        p.put_storage(addr, b"\x01" * 32, 42)
        p.put_storage(addr, b"\x01" * 32, 43)  # overwrite
        p.record_account_change(5, addr, None)
    p = factory.provider()
    assert p.account(addr) == Account(nonce=1, balance=100)
    assert p.account_storage(addr) == {b"\x01" * 32: 43}
    assert p.account_changes_in_range(5, 5) == {addr: None}


def test_kv_basic_and_cursor_order():
    db = MemDb()
    with db.tx_mut() as tx:
        for k in (b"b", b"a", b"c"):
            tx.put("t", k, b"v" + k)
    tx = db.tx()
    cur = tx.cursor("t")
    assert [k for k, _ in cur.walk()] == [b"a", b"b", b"c"]
    assert cur.seek(b"aa") == (b"b", b"vb")
    assert cur.seek_exact(b"aa") is None
    assert cur.seek_exact(b"c") == (b"c", b"vc")
    assert cur.prev() == (b"b", b"vb")
    assert cur.last() == (b"c", b"vc")


def test_abort_rolls_back():
    db = MemDb()
    with db.tx_mut() as tx:
        tx.put("t", b"k", b"v1")
    tx = db.tx_mut()
    tx.put("t", b"k", b"v2")
    tx.put("t", b"k2", b"x")
    tx.delete("t", b"k")
    tx.abort()
    assert db.tx().get("t", b"k") == b"v1"
    assert db.tx().get("t", b"k2") is None


def test_clear_rolls_back():
    db = MemDb()
    with db.tx_mut() as tx:
        tx.put("t", b"k", b"v1")
    tx = db.tx_mut()
    tx.clear("t")
    tx.put("t", b"k3", b"z")
    tx.abort()
    assert db.tx().get("t", b"k") == b"v1"
    assert db.tx().get("t", b"k3") is None


def test_put_then_clear_abort_restores_tx_start():
    """abort after put-then-clear must restore PRE-transaction state."""
    db = MemDb()
    with db.tx_mut() as tx:
        tx.put("t", b"k", b"v1")
    tx = db.tx_mut()
    tx.put("t", b"k", b"v2")
    tx.clear("t")
    tx.put("t", b"k", b"v3")
    tx.abort()
    assert db.tx().get("t", b"k") == b"v1"


def test_dupsort_cursor():
    db = MemDb()
    with db.tx_mut() as tx:
        tx.put("d", b"k1", b"bbb", dupsort=True)
        tx.put("d", b"k1", b"aaa", dupsort=True)
        tx.put("d", b"k1", b"ccc", dupsort=True)
        tx.put("d", b"k2", b"zzz", dupsort=True)
    cur = db.tx().cursor("d")
    assert list(cur.walk_dup(b"k1")) == [(b"k1", b"aaa"), (b"k1", b"bbb"), (b"k1", b"ccc")]
    assert cur.seek_by_key_subkey(b"k1", b"bb") == (b"k1", b"bbb")
    assert cur.seek_by_key_subkey(b"k1", b"zzz") is None
    # full walk visits each dup
    assert [v for _, v in db.tx().cursor("d").walk()] == [b"aaa", b"bbb", b"ccc", b"zzz"]
    # delete one dup
    with db.tx_mut() as tx:
        assert tx.delete("d", b"k1", b"bbb")
    assert list(db.tx().cursor("d").walk_dup(b"k1")) == [(b"k1", b"aaa"), (b"k1", b"ccc")]


def test_walk_range():
    db = MemDb()
    with db.tx_mut() as tx:
        for i in range(10):
            tx.put("t", be64(i), bytes([i]))
    got = [k for k, _ in db.tx().cursor("t").walk_range(be64(3), be64(7))]
    assert got == [be64(i) for i in range(3, 7)]


def test_persistence_roundtrip(tmp_path):
    path = tmp_path / "db.bin"
    db = MemDb(path)
    with db.tx_mut() as tx:
        tx.put("t", b"k", b"v")
    db.flush()
    db2 = MemDb(path)
    assert db2.tx().get("t", b"k") == b"v"


def test_provider_blocks_and_state():
    factory = ProviderFactory(MemDb())
    header = Header(number=1, base_fee_per_gas=7)
    tx0 = Transaction(tx_type=2, chain_id=1, to=b"\x01" * 20, value=5, r=1, s=1)
    block = Block(header, (tx0,), (), (Withdrawal(0, 0, b"\x02" * 20, 1),))
    with factory.provider_rw() as p:
        p.insert_header(header)
        p.insert_block_body(block)
        p.put_sender(0, b"\x0a" * 20)
        p.put_receipt(0, Receipt(tx_type=2, success=True, cumulative_gas_used=21000,
                                 logs=(Log(b"\x01" * 20, (b"\x02" * 32,), b"d"),)))
        p.put_account(b"\x0a" * 20, Account(nonce=1, balance=100))
        p.put_storage(b"\x0a" * 20, b"\x01" * 32, 42)

    p = factory.provider()
    assert p.header_by_number(1) == header
    assert p.canonical_hash(1) == header.hash
    assert p.block_number(header.hash) == 1
    got = p.block_by_number(1)
    assert got == block
    assert p.sender(0) == b"\x0a" * 20
    assert p.receipt(0).cumulative_gas_used == 21000
    assert p.account(b"\x0a" * 20) == Account(nonce=1, balance=100)
    assert p.storage(b"\x0a" * 20, b"\x01" * 32) == 42
    assert p.storage(b"\x0a" * 20, b"\x02" * 32) == 0
    idx = p.block_body_indices(1)
    assert (idx.first_tx_num, idx.tx_count) == (0, 1)


def test_provider_storage_overwrite_and_zero():
    factory = ProviderFactory(MemDb())
    addr = b"\x0b" * 20
    with factory.provider_rw() as p:
        p.put_storage(addr, b"\x01" * 32, 1)
        p.put_storage(addr, b"\x01" * 32, 2)  # overwrite, not duplicate
        p.put_storage(addr, b"\x02" * 32, 3)
        p.put_storage(addr, b"\x02" * 32, 0)  # delete
    p = factory.provider()
    assert p.account_storage(addr) == {b"\x01" * 32: 2}


def test_changesets_first_seen_wins():
    factory = ProviderFactory(MemDb())
    addr = b"\x0c" * 20
    with factory.provider_rw() as p:
        p.record_account_change(5, addr, Account(balance=1))
        p.record_account_change(6, addr, Account(balance=2))
        p.record_storage_change(5, addr, b"\x01" * 32, 10)
        p.record_storage_change(6, addr, b"\x01" * 32, 20)
    p = factory.provider()
    assert p.account_changes_in_range(5, 6)[addr] == Account(balance=1)
    assert p.account_changes_in_range(6, 6)[addr] == Account(balance=2)
    assert p.storage_changes_in_range(5, 6)[addr][b"\x01" * 32] == 10


def test_trie_branch_storage():
    factory = ProviderFactory(MemDb())
    node = BranchNode(0b11, 0b01, 0b10, (b"\xaa" * 32,))
    with factory.provider_rw() as p:
        p.put_account_branch(b"\x01\x02", node)
        p.put_storage_branch(b"\xbb" * 32, b"\x03", node)
        p.put_storage_branch(b"\xbb" * 32, b"\x03", BranchNode(0b1, 0, 0, ()))  # overwrite
    p = factory.provider()
    assert p.account_branch(b"\x01\x02") == node
    assert p.storage_branch(b"\xbb" * 32, b"\x03") == BranchNode(0b1, 0, 0, ())
    assert p.storage_branch(b"\xbb" * 32, b"\x04") is None


def test_stage_checkpoints():
    factory = ProviderFactory(MemDb())
    with factory.provider_rw() as p:
        assert p.stage_checkpoint("Headers") == 0
        p.save_stage_checkpoint("Headers", 100)
    assert factory.provider().stage_checkpoint("Headers") == 100
