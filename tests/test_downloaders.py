"""Downloader scale: reverse tip→local header sync + concurrent body
windows over multiple peers with out-of-order reassembly and reputation
feedback.

Reference analogue: crates/net/downloaders — reverse_headers.rs (headers
authenticate by hash-linking down from a trusted tip hash) and
src/bodies/ (windowed concurrent body scheduling).
"""

from __future__ import annotations

import random
import threading

import pytest

from reth_tpu.consensus import EthBeaconConsensus
from reth_tpu.net.downloader import (
    BodiesDownloader,
    PeerError,
    download_headers_reverse,
)
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.primitives.types import Header
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)


def build_chain(n=24):
    alice = Wallet(0xA11CE)
    bld = ChainBuilder({alice.address: Account(balance=10**21)}, committer=CPU)
    for i in range(n):
        bld.build_block([alice.transfer(b"\x0b" * 20, 100 + i)])
    return bld


class _Body:
    def __init__(self, block):
        self.transactions = block.transactions
        self.ommers = block.ommers
        self.withdrawals = block.withdrawals


class MockPeer:
    """A header/body server over a built chain (PeerConnection shape)."""

    def __init__(self, builder, shuffle_delay=False, tamper_header=None,
                 lie_bodies=False):
        self.by_hash = {b.hash: b for b in builder.blocks}
        self.by_number = {b.header.number: b for b in builder.blocks}
        self.shuffle_delay = shuffle_delay
        self.tamper_header = tamper_header
        self.lie_bodies = lie_bodies
        self.requests = 0

    def get_headers(self, start, limit, reverse=False, skip=0):
        self.requests += 1
        if isinstance(start, bytes):
            blk = self.by_hash.get(start)
        else:
            blk = self.by_number.get(start)
        out = []
        while blk is not None and len(out) < limit:
            h = blk.header
            if self.tamper_header is not None and h.number == self.tamper_header:
                h = Header(**{**h.__dict__, "gas_used": h.gas_used + 1})
            out.append(h)
            nxt = h.number - 1 if reverse else h.number + 1
            blk = self.by_number.get(nxt)
        return out

    def get_bodies(self, hashes):
        self.requests += 1
        if self.shuffle_delay:
            import time

            time.sleep(random.random() * 0.02)
        if self.lie_bodies:
            # serve the WRONG body for every hash (previous block's txs)
            return [_Body(self.by_number[max(0, self.by_hash[h].header.number - 1)])
                    for h in hashes]
        return [_Body(self.by_hash[h]) for h in hashes]


def test_reverse_headers_from_tip_hash():
    """The downloader only knows the tip HASH; headers arrive ascending,
    each authenticated by hashing into its child."""
    bld = build_chain(24)
    peer = MockPeer(bld)
    tip = bld.tip
    headers = download_headers_reverse(peer, tip.hash, 0, batch=7)
    assert [h.number for h in headers] == list(range(1, 25))
    assert headers[-1].hash == tip.hash
    # partial range: stop above local block 10
    headers = download_headers_reverse(peer, tip.hash, 10, batch=7)
    assert [h.number for h in headers] == list(range(11, 25))


def test_reverse_headers_reject_tampered():
    """A tampered header anywhere in the range breaks the hash link and
    is rejected — the lying peer cannot inject data below the tip."""
    bld = build_chain(12)
    peer = MockPeer(bld, tamper_header=6)
    with pytest.raises(PeerError, match="hash-link"):
        download_headers_reverse(peer, bld.tip.hash, 0, batch=5)


def test_bodies_windows_out_of_order_two_peers():
    """Two peers with random response delays: windows complete out of
    order, reassembly is exact, and BOTH peers actually served windows."""
    bld = build_chain(32)
    headers = [b.header for b in bld.blocks[1:]]
    p1 = MockPeer(bld, shuffle_delay=True)
    p2 = MockPeer(bld, shuffle_delay=True)
    dl = BodiesDownloader([p1, p2], window=4,
                          consensus=EthBeaconConsensus(CPU))
    blocks = dl.download(headers)
    assert [b.header.number for b in blocks] == list(range(1, 33))
    assert all(b.hash == bld.blocks[b.header.number].hash for b in blocks)
    assert len(dl.stats) == 2 and all(v > 0 for v in dl.stats.values())


def test_bodies_lying_peer_penalized_and_requeued():
    """A peer serving wrong bodies is penalized through the reputation
    sink and retired; its windows re-queue to the healthy peer and the
    download still completes correctly."""
    bld = build_chain(16)
    headers = [b.header for b in bld.blocks[1:]]
    liar = MockPeer(bld, lie_bodies=True)
    honest = MockPeer(bld)
    reports = []
    dl = BodiesDownloader([liar, honest], window=4,
                          reporter=lambda peer, kind: reports.append((peer, kind)),
                          consensus=EthBeaconConsensus(CPU))
    blocks = dl.download(headers)
    assert [b.header.number for b in blocks] == list(range(1, 17))
    assert reports and all(p is liar for p, _ in reports)
    assert dl.stats.get(1, 0) == 4  # honest peer served every window


def test_bodies_all_peers_bad_raises():
    bld = build_chain(8)
    headers = [b.header for b in bld.blocks[1:]]
    dl = BodiesDownloader([MockPeer(bld, lie_bodies=True)], window=4,
                          consensus=EthBeaconConsensus(CPU))
    with pytest.raises(PeerError, match="unserved"):
        dl.download(headers)


def test_full_block_client_by_hash_and_range():
    """FullBlockClient seals header+body pairs: header matches the
    requested hash, bodies validate against their headers; range returns
    blocks descending (reference full_block.rs semantics)."""
    from reth_tpu.net.downloader import FullBlockClient

    bld = build_chain(10)
    client = FullBlockClient(MockPeer(bld), EthBeaconConsensus(CPU))
    target = bld.blocks[7]
    blk = client.get_full_block(target.hash)
    assert blk.hash == target.hash and len(blk.transactions) == 1
    rng = client.get_full_block_range(target.hash, 4)
    assert [b.header.number for b in rng] == [7, 6, 5, 4]
    assert all(b.hash == bld.blocks[b.header.number].hash for b in rng)


def test_full_block_client_retries_bad_bodies():
    """A client serving wrong bodies exhausts retries with PeerError."""
    from reth_tpu.net.downloader import FullBlockClient

    bld = build_chain(6)
    liar = MockPeer(bld, lie_bodies=True)
    client = FullBlockClient(liar, EthBeaconConsensus(CPU))
    with pytest.raises(PeerError, match="failed validation"):
        client.get_full_block(bld.blocks[4].hash)
    assert liar.requests >= 3  # bounded retries actually happened


def test_full_block_client_mid_list_omission():
    """Regression (round-4 review): GetBlockBodies OMITS unknown hashes —
    a body missing MID-list must not shift later bodies onto wrong
    headers; the client realigns and refetches only the hole."""
    from reth_tpu.net.downloader import FullBlockClient

    bld = build_chain(8)

    class HolePeer(MockPeer):
        def __init__(self, builder, missing_number):
            super().__init__(builder)
            self.missing = missing_number

        def get_bodies(self, hashes):
            self.requests += 1
            return [_Body(self.by_hash[h]) for h in hashes
                    if self.by_hash[h].header.number != self.missing]

    peer = HolePeer(bld, missing_number=4)
    client = FullBlockClient(peer, EthBeaconConsensus(CPU))
    # the hole never fills -> PeerError; but every OTHER block aligned
    with pytest.raises(PeerError, match="1 bodies failed"):
        client.get_full_block_range(bld.blocks[6].hash, 5)  # blocks 6..2

    # transient hole: second request serves it -> full success
    class FlakyPeer(HolePeer):
        def get_bodies(self, hashes):
            if self.requests >= 2:  # headers req counted too; heal later
                self.missing = -1
            return super().get_bodies(hashes)

    peer2 = FlakyPeer(bld, missing_number=4)
    client2 = FullBlockClient(peer2, EthBeaconConsensus(CPU))
    rng = client2.get_full_block_range(bld.blocks[6].hash, 5)
    assert [b.header.number for b in rng] == [6, 5, 4, 3, 2]
    assert all(b.hash == bld.blocks[b.header.number].hash for b in rng)


def test_full_block_client_corrupt_body_does_not_starve():
    """Regression (round-4 review): one corrupt body in a response must
    not starve the remaining valid bodies — it is discarded by tx-root
    matching and only ITS block refetches."""
    from reth_tpu.net.downloader import FullBlockClient

    bld = build_chain(8)

    class OneCorrupt(MockPeer):
        def __init__(self, builder):
            super().__init__(builder)
            self.corrupted_once = False

        def get_bodies(self, hashes):
            self.requests += 1
            out = [_Body(self.by_hash[h]) for h in hashes]
            if not self.corrupted_once and len(out) >= 3:
                # swap in a foreign body (different block's txs) mid-list
                out[1] = _Body(self.by_number[1])
                self.corrupted_once = True
            return out

    peer = OneCorrupt(bld)
    client = FullBlockClient(peer, EthBeaconConsensus(CPU))
    rng = client.get_full_block_range(bld.blocks[7].hash, 5)  # 7..3
    assert [b.header.number for b in rng] == [7, 6, 5, 4, 3]
    assert all(b.hash == bld.blocks[b.header.number].hash for b in rng)
