"""RPC compliance battery: response SHAPES across every namespace of a
live node (reference crates/rpc/rpc-e2e-tests — execution-apis-style
conformance: hex quantity/data formats, field presence, null semantics)."""

import json
import re
import urllib.request

import pytest

from reth_tpu.node import Node, NodeConfig
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.rpc.convert import data
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)

QTY = re.compile(r"^0x(0|[1-9a-f][0-9a-f]*)$")          # no leading zeros
DATA = re.compile(r"^0x(?:[0-9a-f][0-9a-f])*$")          # even-length hex
HASH32 = re.compile(r"^0x[0-9a-f]{64}$")
ADDR = re.compile(r"^0x[0-9a-f]{40}$")
BLOOM = re.compile(r"^0x[0-9a-f]{512}$")


def rpc_raw(port, method, *params):
    req = json.dumps({"jsonrpc": "2.0", "id": 7, "method": method,
                      "params": list(params)})
    resp = urllib.request.urlopen(
        urllib.request.Request(f"http://127.0.0.1:{port}/", req.encode(),
                               {"Content-Type": "application/json"}),
        timeout=30)
    return json.loads(resp.read())


def rpc(port, method, *params):
    out = rpc_raw(port, method, *params)
    assert out.get("jsonrpc") == "2.0" and out.get("id") == 7
    assert "error" not in out, f"{method}: {out.get('error')}"
    return out["result"]


@pytest.fixture(scope="module")
def live():
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)}, committer=CPU)
    cfg = NodeConfig(dev=True, genesis_header=builder.genesis,
                     genesis_alloc=builder.accounts_at_genesis)
    n = Node(cfg, committer=CPU)
    n.start_rpc()
    # mine two blocks with activity
    port = n.rpc.port
    tx = alice.transfer(b"\x0b" * 20, 4242)
    rpc(port, "eth_sendRawTransaction", data(tx.encode()))
    n.miner.mine_block()
    tx2 = alice.transfer(b"\x0c" * 20, 11)
    rpc(port, "eth_sendRawTransaction", data(tx2.encode()))
    n.miner.mine_block()
    yield n, alice, tx
    n.stop()


def test_quantity_formats(live):
    n, alice, _ = live
    port = n.rpc.port
    for method, params in [
        ("eth_blockNumber", []),
        ("eth_chainId", []),
        ("eth_gasPrice", []),
        ("eth_getBalance", [data(alice.address), "latest"]),
        ("eth_getTransactionCount", [data(alice.address), "latest"]),
        ("eth_getBlockTransactionCountByNumber", ["latest"]),
        ("eth_maxPriorityFeePerGas", []),
    ]:
        got = rpc(port, method, *params)
        assert isinstance(got, str) and QTY.match(got), (method, got)


def test_block_object_shape(live):
    n, _, _ = live
    blk = rpc(n.rpc.port, "eth_getBlockByNumber", "0x1", True)
    for field, pat in [("hash", HASH32), ("parentHash", HASH32),
                       ("stateRoot", HASH32), ("transactionsRoot", HASH32),
                       ("receiptsRoot", HASH32), ("miner", ADDR),
                       ("logsBloom", BLOOM), ("number", QTY),
                       ("gasLimit", QTY), ("gasUsed", QTY),
                       ("timestamp", QTY), ("baseFeePerGas", QTY),
                       ("extraData", DATA)]:
        assert field in blk, field
        assert pat.match(blk[field]), (field, blk[field])
    assert isinstance(blk["transactions"], list) and blk["transactions"]
    tx = blk["transactions"][0]
    for field, pat in [("hash", HASH32), ("from", ADDR), ("nonce", QTY),
                       ("blockNumber", QTY), ("transactionIndex", QTY),
                       ("value", QTY), ("gas", QTY), ("input", DATA),
                       ("type", QTY)]:
        assert pat.match(tx[field]), (field, tx[field])
    # hydrated=false returns hashes only
    blk2 = rpc(n.rpc.port, "eth_getBlockByNumber", "0x1", False)
    assert all(HASH32.match(t) for t in blk2["transactions"])


def test_receipt_and_logs_shape(live):
    n, _, tx = live
    rec = rpc(n.rpc.port, "eth_getTransactionReceipt", data(tx.hash))
    for field, pat in [("transactionHash", HASH32), ("blockHash", HASH32),
                       ("blockNumber", QTY), ("transactionIndex", QTY),
                       ("from", ADDR), ("cumulativeGasUsed", QTY),
                       ("gasUsed", QTY), ("status", QTY),
                       ("effectiveGasPrice", QTY), ("type", QTY),
                       ("logsBloom", BLOOM)]:
        assert field in rec and pat.match(rec[field]), (field, rec.get(field))
    assert isinstance(rec["logs"], list)
    assert rec["contractAddress"] is None  # transfer: no deploy


def test_null_semantics(live):
    n, _, _ = live
    port = n.rpc.port
    assert rpc(port, "eth_getBlockByNumber", "0xdeadbeef", False) is None
    assert rpc(port, "eth_getTransactionReceipt", "0x" + "ab" * 32) is None
    assert rpc(port, "eth_getTransactionByHash", "0x" + "ab" * 32) is None
    assert rpc(port, "eth_getBlockByHash", "0x" + "cd" * 32, False) is None


def test_error_codes(live):
    n, _, _ = live
    port = n.rpc.port
    out = rpc_raw(port, "eth_nonexistentMethod")
    assert out["error"]["code"] == -32601
    out = rpc_raw(port, "eth_getBalance")  # missing params
    assert out["error"]["code"] in (-32602, -32603)
    out = rpc_raw(port, "eth_sendRawTransaction", "0xzz")
    assert out["error"]["code"] in (-32602, -32000, -32603)


def test_namespace_coverage(live):
    """Every advertised namespace answers its flagship method."""
    n, alice, _ = live
    port = n.rpc.port
    assert rpc(port, "web3_clientVersion").startswith("reth-tpu/")
    assert HASH32.match(rpc(port, "web3_sha3", "0x68656c6c6f20776f726c64"))
    assert rpc(port, "net_version") == "1"
    assert rpc(port, "net_listening") in (True, False)
    assert QTY.match(rpc(port, "net_peerCount"))
    pool = rpc(port, "txpool_status")
    assert QTY.match(pool["pending"]) and QTY.match(pool["queued"])
    fee = rpc(port, "eth_feeHistory", "0x2", "latest", [25, 75])
    assert QTY.match(fee["oldestBlock"])
    assert all(QTY.match(x) for x in fee["baseFeePerGas"])
    sync = rpc(port, "eth_syncing")
    assert sync is False or isinstance(sync, dict)
    proof = rpc(port, "eth_getProof", data(alice.address), [], "latest")
    assert ADDR.match(proof["address"]) and proof["accountProof"]
    assert all(DATA.match(x) for x in proof["accountProof"])
    trace = rpc(port, "debug_getRawHeader", "0x1")
    assert DATA.match(trace)
    ots = rpc(port, "ots_getApiLevel")
    assert isinstance(ots, int)


def test_eth_call_and_estimate_shapes(live):
    n, alice, _ = live
    port = n.rpc.port
    call = {"to": data(b"\x0b" * 20), "from": data(alice.address),
            "value": "0x0"}
    assert DATA.match(rpc(port, "eth_call", call, "latest"))
    assert QTY.match(rpc(port, "eth_estimateGas", call))
    code = rpc(port, "eth_getCode", data(b"\x0b" * 20), "latest")
    assert code == "0x"
    slot = rpc(port, "eth_getStorageAt", data(b"\x0b" * 20),
               "0x0", "latest")
    assert HASH32.match(slot)
