"""Proof-revealed sparse trie: reveal/read/update/delete, level-batched
rehash parity with the committer, blinded-node semantics, and the
cross-block preserved cache (reference crates/trie/sparse +
chain-state/src/preserved_sparse_trie.rs)."""

import numpy as np
import pytest

from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256, keccak256_batch_np
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.storage.tables import encode_account
from reth_tpu.trie import TrieCommitter
from reth_tpu.trie.incremental import full_state_root
from reth_tpu.trie.naive import naive_trie_root
from reth_tpu.trie.proof import ProofCalculator
from reth_tpu.trie.sparse import (
    BlindedNodeError,
    PreservedSparseTrie,
    SparseStateTrie,
    SparseTrie,
    export_branch_updates,
)

CPU = TrieCommitter(hasher=keccak256_batch_np)


def setup_state(n_accounts=60):
    rng = np.random.default_rng(11)
    factory = ProviderFactory(MemDb())
    addresses = [bytes(rng.integers(0, 256, 20, dtype=np.uint8))
                 for _ in range(n_accounts)]
    with factory.provider_rw() as p:
        for i, a in enumerate(addresses):
            p.put_hashed_account(keccak256(a), Account(nonce=i, balance=1000 + i))
        root = full_state_root(p, CPU)
    leaves = {keccak256(a): encode_account(Account(nonce=i, balance=1000 + i))
              for i, a in enumerate(addresses)}
    return factory, addresses, root, leaves


def leaves_of(factory):
    with factory.provider() as p:
        return {h: encode_account(acct) for h, acct in p.iter_hashed_accounts()}


def test_reveal_and_get():
    factory, addrs, root, base_leaves = setup_state()
    trie = SparseTrie(root)
    with factory.provider() as p:
        calc = ProofCalculator(p, CPU)
        pr = calc.account_proof(addrs[3])
    trie.reveal(pr.proof)
    got = trie.get(keccak256(addrs[3]))
    assert got == encode_account(Account(nonce=3, balance=1003))
    # unrevealed sibling path raises with the blinded path attached
    with pytest.raises(BlindedNodeError) as ei:
        trie.get(keccak256(addrs[40]))
    assert isinstance(ei.value.path, bytes)


def test_update_and_root_parity():
    """Reveal spines for touched keys, update, rehash — root must equal a
    full recompute over the final leaf set."""
    factory, addrs, root, base_leaves = setup_state()
    trie = SparseTrie(root)
    touched = addrs[:8]
    with factory.provider() as p:
        calc = ProofCalculator(p, CPU)
        for a in touched:
            trie.reveal(calc.account_proof(a).proof)
    leaves = dict(base_leaves)
    for i, a in enumerate(touched):
        new = encode_account(Account(nonce=100 + i, balance=5))
        trie.update(keccak256(a), new)
        leaves[keccak256(a)] = new
    got = trie.root_hash_compute()
    assert got == naive_trie_root(leaves)


def test_insert_new_keys_and_delete():
    factory, addrs, root, base_leaves = setup_state(20)
    trie = SparseTrie(root)
    leaves = dict(base_leaves)
    fresh = b"\xaa" * 20
    with factory.provider() as p:
        calc = ProofCalculator(p, CPU)
        # exclusion proof reveals the insertion path for the fresh key
        trie.reveal(calc.account_proof(fresh).proof)
        trie.reveal(calc.account_proof(addrs[5]).proof)
    new_val = encode_account(Account(balance=77))
    trie.update(keccak256(fresh), new_val)
    leaves[keccak256(fresh)] = new_val
    assert trie.root_hash_compute() == naive_trie_root(leaves)
    # delete it again: back to the original root
    trie.delete(keccak256(fresh))
    del leaves[keccak256(fresh)]
    assert trie.root_hash_compute() == naive_trie_root(leaves)
    assert trie.root_hash_compute() == root


def test_delete_collapse_needs_sibling_reveal():
    """Deleting down to a single-sibling branch must either collapse (when
    the sibling is revealed) or raise BlindedNodeError naming its path."""
    # two keys sharing no prefix structure constraints: build a tiny trie
    leaves = {}
    t = SparseTrie()
    a, b = b"\x11" * 32, b"\x12" * 32  # diverge at nibble 1
    va, vb = b"A-value", b"B-value"
    t.update(a, va)
    t.update(b, vb)
    leaves[a], leaves[b] = va, vb
    root = t.root_hash_compute()
    assert root == naive_trie_root(leaves)
    # fresh trie anchored at that root, reveal only a's spine
    spine_a = t.spine(a)
    t2 = SparseTrie(root)
    t2.reveal(spine_a)
    with pytest.raises(BlindedNodeError) as ei:
        t2.delete(a)  # survivor (b's subtree) is blinded -> cannot collapse
    # reveal the survivor and retry
    t2b = SparseTrie(root)
    t2b.reveal(spine_a)
    t2b.reveal(t.spine(b))
    t2b.delete(a)
    assert t2b.root_hash_compute() == naive_trie_root({b: vb})
    assert len(ei.value.path) >= 1


def test_sparse_state_trie_with_storage():
    rng = np.random.default_rng(5)
    factory = ProviderFactory(MemDb())
    addr = b"\x42" * 20
    slots = {bytes(rng.integers(0, 256, 32, dtype=np.uint8)): int(v)
             for v in rng.integers(1, 2**40, size=5)}
    with factory.provider_rw() as p:
        p.put_hashed_account(keccak256(addr), Account(balance=9))
        for s, v in slots.items():
            p.put_hashed_storage(keccak256(addr), keccak256(s), v)
        root = full_state_root(p, CPU)
    st = SparseStateTrie.anchored(root)
    target_slot = next(iter(slots))
    with factory.provider() as p:
        calc = ProofCalculator(p, CPU)
        pr = calc.account_proof(addr, [target_slot])
    st.reveal_account(pr.proof)
    st.reveal_storage(keccak256(addr), pr.storage_root,
                      pr.storage_proofs[0].proof)
    stg = st.storage_trie(keccak256(addr))
    got = stg.get(keccak256(target_slot))
    from reth_tpu.primitives.rlp import decode_int, rlp_decode
    assert decode_int(rlp_decode(got)) == slots[target_slot]
    # update the slot, recompute storage root, splice into the account
    from reth_tpu.primitives.rlp import encode_int, rlp_encode
    stg.update(keccak256(target_slot), rlp_encode(encode_int(123456)))
    new_sroot = stg.root_hash_compute()
    acct = Account(balance=9, storage_root=new_sroot)
    st.update_account(keccak256(addr), encode_account(acct))
    new_root = st.root()
    # cross-check against the provider path
    with factory.provider_rw() as p:
        p.put_hashed_storage(keccak256(addr), keccak256(target_slot), 123456)
        p.put_hashed_account(keccak256(addr), acct)
        want = full_state_root(p, CPU)
    assert new_root == want


def test_preserved_cache_semantics():
    cache = PreservedSparseTrie()
    t = SparseStateTrie.anchored(b"\x01" * 32)
    cache.preserve(b"\xbb" * 32, t)
    assert cache.take(b"\xcc" * 32) is None      # wrong anchor: miss
    cache.preserve(b"\xbb" * 32, t)
    got = cache.take(b"\xbb" * 32)
    assert got is t
    assert cache.take(b"\xbb" * 32) is None      # consumed
    assert cache.hits == 1 and cache.misses == 2


def test_clean_subtree_refs_cached_across_roots():
    """Second root() after touching ONE key must re-encode only the dirty
    spine — verified by hasher call sizes (the cross-block win)."""
    factory, addrs, root, base_leaves = setup_state(40)
    trie = SparseTrie(root)
    with factory.provider() as p:
        calc = ProofCalculator(p, CPU)
        for a in addrs:
            trie.reveal(calc.account_proof(a).proof)
    calls = []

    def counting_hasher(msgs):
        calls.append(len(msgs))
        return keccak256_batch_np(msgs)

    trie.root_hash_compute(counting_hasher)
    first_total = sum(calls)
    calls.clear()
    trie.update(keccak256(addrs[0]),
                encode_account(Account(balance=31337)))
    got = trie.root_hash_compute(counting_hasher)
    assert sum(calls) < first_total / 2, (calls, first_total)
    leaves = dict(base_leaves)
    leaves[keccak256(addrs[0])] = encode_account(Account(balance=31337))
    assert got == naive_trie_root(leaves)


def test_randomized_churn_parity():
    """Random updates/inserts/deletes on a fully-revealed sparse trie track
    the naive oracle."""
    rng = np.random.default_rng(77)
    leaves = {bytes(rng.integers(0, 256, 32, dtype=np.uint8)):
              bytes(rng.integers(0, 256, int(rng.integers(1, 40)), dtype=np.uint8))
              for _ in range(50)}
    t = SparseTrie()
    for k, v in leaves.items():
        t.update(k, v)
    assert t.root_hash_compute() == naive_trie_root(leaves)
    keys = list(leaves)
    for step in range(60):
        op = rng.integers(0, 3)
        if op == 0 and keys:  # update
            k = keys[int(rng.integers(0, len(keys)))]
            v = bytes(rng.integers(0, 256, int(rng.integers(1, 40)), dtype=np.uint8))
            t.update(k, v)
            leaves[k] = v
        elif op == 1:  # insert
            k = bytes(rng.integers(0, 256, 32, dtype=np.uint8))
            v = bytes(rng.integers(0, 256, 20, dtype=np.uint8))
            t.update(k, v)
            leaves[k] = v
            keys.append(k)
        elif keys:  # delete
            k = keys.pop(int(rng.integers(0, len(keys))))
            t.delete(k)
            del leaves[k]
        if step % 10 == 9:
            assert t.root_hash_compute() == naive_trie_root(leaves), step
    assert t.root_hash_compute() == naive_trie_root(leaves)


# -- export_branch_updates equivalence --------------------------------------


def _committer_branches(leaves):
    """Ground-truth stored branch nodes for a leaf set (full rebuild).
    ``leaves`` maps 32-byte keys -> values."""
    from reth_tpu.primitives.nibbles import unpack_nibbles

    c = TrieCommitter(hasher=keccak256_batch_np)
    res = c.commit(sorted((unpack_nibbles(k), v) for k, v in leaves.items()))
    return res.root, dict(res.branch_nodes)


def _apply_export(stored, updates):
    out = dict(stored)
    for path, node in updates.items():
        if node is None:
            out.pop(path, None)
        else:
            out[path] = node
    return out


def _run_export_case(pre_leaves, deletes, inserts):
    """Build pre-state, apply a delete+insert batch through the sparse
    trie, export updates, and require the applied stored table to equal a
    post-state full rebuild byte-for-byte."""
    _, stored_pre = _committer_branches(pre_leaves)
    trie = SparseTrie()
    for k, v in pre_leaves.items():
        trie.update(k, v)
    trie.root_hash_compute(keccak256_batch_np)
    post = dict(pre_leaves)
    for k in deletes:
        trie.delete(k)
        post.pop(k)
    for k, v in inserts.items():
        trie.update(k, v)
        post[k] = v
    root = trie.root_hash_compute(keccak256_batch_np)
    updates = export_branch_updates(
        trie, list(deletes) + list(inserts), stored_pre.get)
    post_root, stored_post = _committer_branches(post)
    assert root == post_root
    assert _apply_export(stored_pre, updates) == stored_post


def test_export_emits_new_branch_below_collapsed_one():
    """Regression (round-4 review, CONFIRMED): deleting 3b1.. collapses
    the pre-state branch at '03' while inserting 3a2.. creates a NEW
    branch deeper at '03·0a'; the probe-pruning break must not suppress
    the new branch node's emission."""
    def k(nibs):  # 32-byte key with the given leading nibbles
        full = list(nibs) + [0] * (64 - len(nibs))
        return bytes((full[i] << 4) | full[i + 1] for i in range(0, 64, 2))
    pre = {
        k([3, 0xA, 1]): b"v1",
        k([3, 0xB, 1]): b"v2",
        k([5, 1]): b"v3",
    }
    _run_export_case(pre, deletes=[k([3, 0xB, 1])],
                     inserts={k([3, 0xA, 2]): b"v4"})


def test_export_equivalence_randomized():
    """Randomized churn: exported updates applied to the pre-state stored
    table always equal a post-state full rebuild."""
    rng = np.random.default_rng(7)
    for case in range(12):
        n = int(rng.integers(3, 40))
        keys = [bytes(rng.integers(0, 256, size=32, dtype=np.uint8).tolist())
                for _ in range(n)]
        keys = list(dict.fromkeys(keys))
        # force some shared prefixes so collapses/extensions happen
        for i in range(1, len(keys), 3):
            j = int(rng.integers(1, 8))
            keys[i] = keys[0][:j] + keys[i][j:]
        keys = list(dict.fromkeys(keys))
        pre = {kk: bytes([65 + j % 26]) * 3 for j, kk in enumerate(keys)}
        dels = [kk for j, kk in enumerate(keys) if j % 4 == 1]
        ins = {bytes(rng.integers(0, 256, size=32, dtype=np.uint8).tolist()): b"new"
               for _ in range(int(rng.integers(1, 6)))}
        ins.update({kk: b"upd" for j, kk in enumerate(keys) if j % 5 == 2})
        for kk in dels:
            ins.pop(kk, None)
        _run_export_case(pre, dels, ins)
