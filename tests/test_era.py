"""Era1 archives: e2store records, framed snappy, export -> import -> sync."""

from __future__ import annotations

import io

import pytest

from reth_tpu.consensus import EthBeaconConsensus
from reth_tpu.era import (
    Era1Group,
    EraError,
    crc32c,
    export_era,
    import_era,
    read_era1,
    read_records,
    snappy_frame_compress,
    snappy_frame_decompress,
    write_era1,
    write_record,
)
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.stages import Pipeline, default_stages
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.storage.genesis import import_chain, init_genesis
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)


def test_crc32c_check_value():
    # the standard CRC-32C check vector
    assert crc32c(b"123456789") == 0xE3069283


@pytest.mark.parametrize("payload", [b"", b"x", b"hello " * 1000, bytes(range(256)) * 300])
def test_snappy_framed_roundtrip(payload):
    assert snappy_frame_decompress(snappy_frame_compress(payload)) == payload


def test_snappy_framed_rejects_corruption():
    framed = bytearray(snappy_frame_compress(b"data" * 100))
    framed[-1] ^= 0xFF
    with pytest.raises(EraError):
        snappy_frame_decompress(bytes(framed))


def test_e2store_records_roundtrip():
    buf = io.BytesIO()
    write_record(buf, 0x03, b"abc")
    write_record(buf, 0x3265, b"")
    got = list(read_records(buf.getvalue()))
    assert got == [(0x03, b"abc"), (0x3265, b"")]


def _synced_chain(n_blocks=4):
    alice = Wallet(0xE5A)
    bld = ChainBuilder({alice.address: Account(balance=10**21)}, committer=CPU)
    for i in range(n_blocks):
        bld.build_block([alice.transfer(bytes([i + 1] * 20), 1000 + i)])
    factory = ProviderFactory(MemDb())
    init_genesis(factory, bld.genesis, bld.accounts_at_genesis, committer=CPU)
    import_chain(factory, bld.blocks[1:], EthBeaconConsensus(CPU))
    Pipeline(factory, default_stages(committer=CPU)).run(n_blocks)
    return factory, bld


def test_era1_file_roundtrip(tmp_path):
    factory, bld = _synced_chain()
    path = tmp_path / "chain-0.era1"
    n = export_era(factory, 1, 4, path)
    assert n == 4
    group = read_era1(path)
    assert group.start_block == 1
    assert [b.hash for b in group.blocks] == [b.hash for b in bld.blocks[1:]]
    assert all(len(r) == 1 for r in group.receipts)  # one tx per block


def test_era1_import_syncs_fresh_node(tmp_path):
    factory, bld = _synced_chain()
    path = tmp_path / "chain-0.era1"
    export_era(factory, 1, 4, path)

    fresh = ProviderFactory(MemDb())
    init_genesis(fresh, bld.genesis, bld.accounts_at_genesis, committer=CPU)
    tip = import_era(fresh, path, EthBeaconConsensus(CPU))
    assert tip == 4
    Pipeline(fresh, default_stages(committer=CPU)).run(tip)
    with fresh.provider() as p:
        assert p.header_by_number(4).state_root == bld.tip.state_root


def test_era1_write_rejects_oversize(tmp_path):
    with pytest.raises(EraError, match="at most"):
        write_era1(tmp_path / "x.era1",
                   Era1Group(0, [None] * 8193, [None] * 8193, [0] * 8193))
