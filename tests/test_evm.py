"""EVM tests: transfers, contract lifecycle, storage, reverts, gas."""

import pytest

from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256
from reth_tpu.primitives import secp256k1
from reth_tpu.primitives.types import Block, Header, Transaction
from reth_tpu.evm import BlockExecutor, EvmConfig
from reth_tpu.evm.executor import InMemoryStateSource, InvalidTransaction, intrinsic_gas
from reth_tpu.evm.interpreter import BlockEnv, CallFrame, Interpreter, TxEnv
from reth_tpu.evm.state import EvmState

ALICE_KEY = 0xA11CE
ALICE = secp256k1.address_from_priv(ALICE_KEY)
BOB = b"\x0b" * 20
COINBASE = b"\xc0" * 20


def signed_tx(**kw):
    priv = kw.pop("priv", ALICE_KEY)
    defaults = dict(tx_type=2, chain_id=1, nonce=0, max_fee_per_gas=10,
                    max_priority_fee_per_gas=2, gas_limit=21000, to=BOB, value=1000)
    defaults.update(kw)
    tx = Transaction(**defaults)
    p, r, s = secp256k1.sign(tx.signing_hash(), priv)
    return Transaction(**{**tx.__dict__, "y_parity": p, "r": r, "s": s})


def make_block(txs, **hdr):
    defaults = dict(number=1, base_fee_per_gas=7, gas_limit=30_000_000, timestamp=1000)
    defaults.update(hdr)
    return Block(Header(beneficiary=COINBASE, **defaults), tuple(txs))


def rich_source(balance=10**18):
    return InMemoryStateSource({ALICE: Account(balance=balance)})


def test_simple_transfer():
    src = rich_source()
    tx = signed_tx()
    out = BlockExecutor(src).execute(make_block([tx]))
    assert out.receipts[0].success
    assert out.gas_used == 21000
    assert out.post_accounts[BOB].balance == 1000
    # alice: -value -gas*effective_price (base 7 + prio 2 = 9)
    assert out.post_accounts[ALICE].balance == 10**18 - 1000 - 21000 * 9
    # coinbase gets priority fee only
    assert out.post_accounts[COINBASE].balance == 21000 * 2
    assert out.senders == [ALICE]


def test_nonce_and_funds_validation():
    src = rich_source(balance=1)
    with pytest.raises(InvalidTransaction, match="insufficient"):
        BlockExecutor(src).execute(make_block([signed_tx()]))
    src = rich_source()
    with pytest.raises(InvalidTransaction, match="nonce"):
        BlockExecutor(src).execute(make_block([signed_tx(nonce=5)]))


def test_two_txs_sequential_nonces():
    src = rich_source()
    b = make_block([signed_tx(nonce=0), signed_tx(nonce=1, value=500)])
    out = BlockExecutor(src).execute(b)
    assert out.gas_used == 42000
    assert out.post_accounts[BOB].balance == 1500
    assert out.post_accounts[ALICE].nonce == 2


# A contract that stores calldata word0 at slot0:
# PUSH0 CALLDATALOAD PUSH0 SSTORE STOP
STORE_CODE = bytes.fromhex("5f355f5500")
# Runtime-returning initcode for STORE_CODE:
#   PUSH5 <code> PUSH0 MSTORE ... simpler: CODECOPY pattern
# initcode: PUSH1 len PUSH1 off PUSH0 CODECOPY PUSH1 len PUSH0 RETURN <code>
def initcode_for(runtime: bytes) -> bytes:
    n = len(runtime)
    return bytes([0x60, n, 0x60, 0x0B, 0x5F, 0x39, 0x60, n, 0x5F, 0xF3]) + b"\x00" + runtime


def test_create_and_call_contract():
    src = rich_source()
    deploy = signed_tx(to=None, data=initcode_for(STORE_CODE), gas_limit=200_000)
    out = BlockExecutor(src).execute(make_block([deploy]))
    assert out.receipts[0].success
    # locate the created contract account
    created = [a for a, acc in out.post_accounts.items()
               if acc and acc.code_hash != keccak256(b"") and a != ALICE]
    assert len(created) == 1
    contract = created[0]
    assert out.changes.new_bytecodes[keccak256(STORE_CODE)] == STORE_CODE
    # now call it: store 0xdead at slot 0
    src2 = InMemoryStateSource(
        {ALICE: Account(balance=10**18), contract: out.post_accounts[contract]},
        codes={keccak256(STORE_CODE): STORE_CODE},
    )
    call = signed_tx(to=contract, value=0, gas_limit=100_000,
                     data=(0xDEAD).to_bytes(32, "big"))
    out2 = BlockExecutor(src2).execute(make_block([call]))
    assert out2.receipts[0].success
    assert out2.post_storage[contract][b"\x00" * 32] == 0xDEAD
    assert out2.changes.storage[contract][b"\x00" * 32] == 0  # prev value


def test_revert_rolls_back_state():
    # contract: store 1 at slot0 then revert: PUSH1 1 PUSH0 SSTORE PUSH0 PUSH0 REVERT
    code = bytes.fromhex("60015f555f5ffd")
    caddr = b"\x11" * 20
    src = InMemoryStateSource(
        {ALICE: Account(balance=10**18), caddr: Account(code_hash=keccak256(code))},
        codes={keccak256(code): code},
    )
    tx = signed_tx(to=caddr, value=0, gas_limit=100_000)
    out = BlockExecutor(src).execute(make_block([tx]))
    assert not out.receipts[0].success
    assert caddr not in out.post_storage or out.post_storage[caddr].get(b"\x00" * 32, 0) == 0
    # gas was still charged
    assert out.gas_used > 21000


def test_sstore_refund():
    # clear an existing slot: PUSH0 PUSH0 SSTORE (set slot0 = 0)
    code = bytes.fromhex("5f5f5500")
    caddr = b"\x12" * 20
    src = InMemoryStateSource(
        {ALICE: Account(balance=10**18), caddr: Account(code_hash=keccak256(code))},
        storages={caddr: {b"\x00" * 32: 99}},
        codes={keccak256(code): code},
    )
    tx = signed_tx(to=caddr, value=0, gas_limit=100_000)
    out = BlockExecutor(src).execute(make_block([tx]))
    assert out.receipts[0].success
    assert out.post_storage[caddr][b"\x00" * 32] == 0
    # refund (4800) capped at gas_used/5 applied: without refund it'd be
    # 21000 + 2100(cold) + 2900(reset) + 4 = 26004; refund = min(4800, 5200)
    no_refund = 21000 + 2100 + 2900 + 2 + 2
    assert out.gas_used == no_refund - min(4800, no_refund // 5)


def test_log_emission():
    # LOG1 with topic 0x42: PUSH1 0x42 PUSH0 PUSH0 LOG1 STOP
    code = bytes.fromhex("60425f5fa100")
    caddr = b"\x13" * 20
    src = InMemoryStateSource(
        {ALICE: Account(balance=10**18), caddr: Account(code_hash=keccak256(code))},
        codes={keccak256(code): code},
    )
    out = BlockExecutor(src).execute(
        make_block([signed_tx(to=caddr, value=0, gas_limit=100_000)])
    )
    r = out.receipts[0]
    assert r.success and len(r.logs) == 1
    assert r.logs[0].address == caddr
    assert r.logs[0].topics == ((0x42).to_bytes(32, "big"),)


def test_withdrawals_credit():
    from reth_tpu.primitives.types import Withdrawal

    src = InMemoryStateSource({})
    blk = Block(
        Header(number=1, base_fee_per_gas=7, withdrawals_root=b"\x00" * 32),
        (), (), (Withdrawal(0, 1, BOB, 3), Withdrawal(1, 1, BOB, 2)),
    )
    out = BlockExecutor(src).execute(blk)
    assert out.post_accounts[BOB].balance == 5 * 10**9


def test_intrinsic_gas():
    tx = Transaction(tx_type=2, chain_id=1, to=BOB, data=b"\x00\x01\x02")
    assert intrinsic_gas(tx) == 21000 + 4 + 16 + 16
    create = Transaction(tx_type=2, chain_id=1, to=None, data=b"\xff" * 33)
    assert intrinsic_gas(create) == 21000 + 32000 + 33 * 16 + 2 * 2


def test_interpreter_arithmetic_direct():
    """Drive raw opcodes: (3+4)*5 stored to slot0."""
    # PUSH1 3 PUSH1 4 ADD PUSH1 5 MUL PUSH0 SSTORE STOP
    code = bytes.fromhex("60036004016005025f5500")
    state = EvmState(InMemoryStateSource({}))
    interp = Interpreter(state, BlockEnv(), TxEnv())
    ok, gas_left, out = interp.call(
        CallFrame(caller=ALICE, address=b"\x14" * 20, code=code, data=b"", value=0, gas=100_000)
    )
    assert ok
    assert state.sload(b"\x14" * 20, b"\x00" * 32) == 35


def test_precompile_sha256_and_identity():
    state = EvmState(InMemoryStateSource({}))
    interp = Interpreter(state, BlockEnv(), TxEnv())
    import hashlib

    ok, _, out = interp.call(CallFrame(
        caller=ALICE, address=b"\x00" * 19 + b"\x02", code=b"", data=b"abc", value=0, gas=10_000
    ))
    assert ok and out == hashlib.sha256(b"abc").digest()
    ok, _, out = interp.call(CallFrame(
        caller=ALICE, address=b"\x00" * 19 + b"\x04", code=b"", data=b"xyz", value=0, gas=10_000
    ))
    assert ok and out == b"xyz"


def test_delegatecall_does_not_retransfer_value():
    """DELEGATECALL must not move the parent frame's value again."""
    # impl B: STOP. proxy A: DELEGATECALL B then STOP
    impl = bytes.fromhex("00")
    # PUSH0 x4, PUSH20 <B>, GAS, DELEGATECALL, STOP
    b_addr = b"\x1b" * 20
    proxy_code = bytes.fromhex("5f5f5f5f73") + b_addr + bytes.fromhex("5af400")
    a_addr = b"\x1a" * 20
    src = InMemoryStateSource(
        {ALICE: Account(balance=10**18),
         a_addr: Account(code_hash=keccak256(proxy_code)),
         b_addr: Account(code_hash=keccak256(impl))},
        codes={keccak256(proxy_code): proxy_code, keccak256(impl): impl},
    )
    value = 10**17
    tx = signed_tx(to=a_addr, value=value, gas_limit=200_000)
    out = BlockExecutor(src).execute(make_block([tx]))
    assert out.receipts[0].success
    # alice debited exactly once for the value
    fees = out.gas_used * 9
    assert out.post_accounts[ALICE].balance == 10**18 - value - fees
    assert out.post_accounts[a_addr].balance == value


def test_sstore_original_is_tx_start_not_block_start():
    """EIP-2200: 'original' is the value at TX start; two txs hitting the
    same slot in one block must charge reset gas in the second tx."""
    # contract: sstore(0, calldata[0])
    caddr = b"\x21" * 20
    src = InMemoryStateSource(
        {ALICE: Account(balance=10**18), caddr: Account(code_hash=keccak256(STORE_CODE))},
        codes={keccak256(STORE_CODE): STORE_CODE},
    )
    tx1 = signed_tx(to=caddr, value=0, gas_limit=100_000, nonce=0,
                    data=(1).to_bytes(32, "big"))
    tx2 = signed_tx(to=caddr, value=0, gas_limit=100_000, nonce=1,
                    data=(2).to_bytes(32, "big"))
    out = BlockExecutor(src).execute(make_block([tx1, tx2]))
    base = 21000 + 31 * 4 + 16  # intrinsic incl. calldata (31 zero, 1 nonzero)
    g1 = out.receipts[0].cumulative_gas_used
    g2 = out.receipts[1].cumulative_gas_used - g1
    # tx1: cold slot, 0->1 set: 2100 + 20000 (+ code overhead 2+3+2)
    assert g1 == base + 2100 + 20000 + 7
    # tx2: cold again (per-tx warm reset), original=1 -> reset 2900
    assert g2 == base + 2100 + 2900 + 7
    assert out.post_storage[caddr][b"\x00" * 32] == 2


def test_precompiles_are_warm():
    """EIP-2929: precompile CALL costs warm access, not cold."""
    # PUSH0 x5, PUSH1 4 (identity), GAS, STATICCALL, STOP
    code = bytes.fromhex("5f5f5f5f5f60045afa00")
    caddr = b"\x22" * 20
    src = InMemoryStateSource(
        {ALICE: Account(balance=10**18), caddr: Account(code_hash=keccak256(code))},
        codes={keccak256(code): code},
    )
    out = BlockExecutor(src).execute(
        make_block([signed_tx(to=caddr, value=0, gas_limit=100_000)])
    )
    assert out.receipts[0].success
    # 5*PUSH0(2) + PUSH1(3) + GAS(2) + warm access(100) + identity(15)
    assert out.gas_used == 21000 + 5 * 2 + 3 + 2 + 100 + 15


def test_selfdestruct_to_self_keeps_balance():
    """Post-EIP-6780: pre-existing contract SELFDESTRUCT(self) keeps funds."""
    # PUSH20 <self> SELFDESTRUCT
    caddr = b"\x23" * 20
    code = b"\x73" + caddr + b"\xff"
    src = InMemoryStateSource(
        {ALICE: Account(balance=10**18),
         caddr: Account(balance=555, code_hash=keccak256(code))},
        codes={keccak256(code): code},
    )
    out = BlockExecutor(src).execute(
        make_block([signed_tx(to=caddr, value=0, gas_limit=100_000)])
    )
    assert out.receipts[0].success
    acc = out.post_accounts.get(caddr)
    assert acc is not None and acc.balance == 555  # not destroyed, not burned


def test_precompile_ecrecover():
    state = EvmState(InMemoryStateSource({}))
    interp = Interpreter(state, BlockEnv(), TxEnv())
    h = keccak256(b"message")
    parity, r, s = secp256k1.sign(h, ALICE_KEY)
    data = h + (27 + parity).to_bytes(32, "big") + r.to_bytes(32, "big") + s.to_bytes(32, "big")
    ok, _, out = interp.call(CallFrame(
        caller=ALICE, address=b"\x00" * 19 + b"\x01", code=b"", data=data, value=0, gas=10_000
    ))
    assert ok and out[12:] == ALICE
