"""Incremental state-root tests: full-vs-incremental-vs-naive equality.

Mirrors the reference's merkle-stage tests (random state + incremental
parity, crates/stages/stages/src/stages/merkle.rs tests) with direct
control of the hashed tables (keys need not be real keccak images).
"""

import numpy as np

from reth_tpu.primitives import Account, EMPTY_ROOT_HASH
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.primitives.nibbles import unpack_nibbles
from reth_tpu.primitives.rlp import rlp_encode, encode_int
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.storage.tables import encode_account
from reth_tpu.trie import TrieCommitter, naive_trie_root
from reth_tpu.trie.incremental import IncrementalStateRoot, full_state_root, nibbles_range

CPU = TrieCommitter(hasher=keccak256_batch_np)


def naive_state_root(accounts: dict[bytes, Account], storages: dict[bytes, dict[bytes, int]]):
    """Oracle over hashed keys directly."""
    enc = {}
    for hk, acc in accounts.items():
        sroot = EMPTY_ROOT_HASH
        slots = {s: v for s, v in storages.get(hk, {}).items() if v}
        if slots:
            sroot = naive_trie_root(
                {s: rlp_encode(encode_int(v)) for s, v in slots.items()}
            )
        if acc.is_empty and sroot == EMPTY_ROOT_HASH:
            continue
        enc[hk] = encode_account(acc.with_(storage_root=sroot))
    return naive_trie_root(enc)


def write_hashed_state(p, accounts, storages):
    for hk, acc in accounts.items():
        p.put_hashed_account(hk, acc)
    for hk, slots in storages.items():
        for s, v in slots.items():
            p.put_hashed_storage(hk, s, v)


def test_nibbles_range():
    start, end = nibbles_range(b"\x01\x02")
    assert start == bytes.fromhex("12" + "00" * 31)
    assert end == bytes.fromhex("13" + "00" * 31)
    start, end = nibbles_range(b"")
    assert start == b"\x00" * 32 and end is None
    start, end = nibbles_range(b"\x0f" * 64)
    assert end is None


def test_full_then_incremental_simple():
    factory = ProviderFactory(MemDb())
    accounts = {
        bytes.fromhex("11" + "00" * 30 + "01"): Account(balance=1),
        bytes.fromhex("12" + "00" * 30 + "02"): Account(balance=2),
        bytes.fromhex("22" + "00" * 30 + "03"): Account(balance=3),
    }
    with factory.provider_rw() as p:
        write_hashed_state(p, accounts, {})
        root = full_state_root(p, CPU)
        assert root == naive_state_root(accounts, {})

    # update one account incrementally
    k = list(accounts)[0]
    accounts[k] = Account(balance=100)
    with factory.provider_rw() as p:
        p.put_hashed_account(k, accounts[k])
        inc = IncrementalStateRoot(p, CPU)
        root = inc.compute({k})
        assert root == naive_state_root(accounts, {})


def test_incremental_deletion_collapse():
    """Deleting a sibling collapses a branch into an unchanged boundary."""
    factory = ProviderFactory(MemDb())
    k1 = bytes.fromhex("11" + "aa" * 31)
    k2 = bytes.fromhex("12" + "bb" * 31)
    k3 = bytes.fromhex("22" + "cc" * 31)
    accounts = {k1: Account(balance=1), k2: Account(balance=2), k3: Account(balance=3)}
    with factory.provider_rw() as p:
        write_hashed_state(p, accounts, {})
        assert full_state_root(p, CPU) == naive_state_root(accounts, {})

    del accounts[k2]
    with factory.provider_rw() as p:
        p.put_hashed_account(k2, None)
        root = IncrementalStateRoot(p, CPU).compute({k2})
        assert root == naive_state_root(accounts, {})
        # stored branch at path [1] must be gone (collapsed)
        assert p.account_branch(b"\x01") is None
        # and a no-change recompute from stored structure still agrees
        assert IncrementalStateRoot(p, CPU).compute(set()) == root


def test_incremental_randomised_churn():
    rng = np.random.default_rng(77)
    factory = ProviderFactory(MemDb())
    accounts: dict[bytes, Account] = {}
    storages: dict[bytes, dict[bytes, int]] = {}

    def rand_key():
        return bytes(rng.integers(0, 256, size=32, dtype=np.uint8))

    # initial population
    for _ in range(120):
        accounts[rand_key()] = Account(
            nonce=int(rng.integers(0, 9)), balance=int(rng.integers(1, 10**12))
        )
    keys = list(accounts)
    for hk in keys[:20]:
        storages[hk] = {
            rand_key(): int(rng.integers(1, 2**60)) for _ in range(int(rng.integers(1, 6)))
        }
    with factory.provider_rw() as p:
        write_hashed_state(p, accounts, storages)
        assert full_state_root(p, CPU) == naive_state_root(accounts, storages)

    for round_i in range(6):
        changed_accounts: set[bytes] = set()
        changed_storages: dict[bytes, set[bytes]] = {}
        wiped: set[bytes] = set()
        with factory.provider_rw() as p:
            # mutate accounts: update / insert / delete
            for _ in range(12):
                op = rng.integers(0, 3)
                if op == 0 and accounts:  # update
                    hk = list(accounts)[int(rng.integers(0, len(accounts)))]
                    accounts[hk] = accounts[hk].with_(balance=int(rng.integers(1, 10**12)))
                    p.put_hashed_account(hk, accounts[hk])
                    changed_accounts.add(hk)
                elif op == 1:  # insert
                    hk = rand_key()
                    accounts[hk] = Account(balance=int(rng.integers(1, 10**12)))
                    p.put_hashed_account(hk, accounts[hk])
                    changed_accounts.add(hk)
                elif accounts:  # delete
                    hk = list(accounts)[int(rng.integers(0, len(accounts)))]
                    del accounts[hk]
                    p.put_hashed_account(hk, None)
                    changed_accounts.add(hk)
                    if hk in storages:
                        for s in storages.pop(hk):
                            p.put_hashed_storage(hk, s, 0)
                        wiped.add(hk)
            # mutate storage slots
            for _ in range(6):
                cands = [a for a in accounts if a in storages]
                if cands:
                    hk = cands[int(rng.integers(0, len(cands)))]
                    slot = rand_key() if rng.integers(0, 2) else list(storages[hk])[0]
                    val = int(rng.integers(0, 2**60))
                    if val:
                        storages[hk][slot] = val
                    else:
                        storages[hk].pop(slot, None)
                    p.put_hashed_storage(hk, slot, val)
                    changed_storages.setdefault(hk, set()).add(slot)
            root = IncrementalStateRoot(p, CPU).compute(
                changed_accounts, changed_storages, wiped
            )
            want = naive_state_root(accounts, storages)
            assert root == want, f"round {round_i} diverged"
            # stored-structure consistency
            assert IncrementalStateRoot(p, CPU).compute(set()) == want


def test_wiped_storage():
    factory = ProviderFactory(MemDb())
    hk = b"\x33" * 32
    slots = {b"\x01" * 32: 5, b"\x02" * 32: 6}
    accounts = {hk: Account(balance=9)}
    with factory.provider_rw() as p:
        write_hashed_state(p, accounts, {hk: slots})
        assert full_state_root(p, CPU) == naive_state_root(accounts, {hk: slots})
    with factory.provider_rw() as p:
        for s in slots:
            p.put_hashed_storage(hk, s, 0)
        root = IncrementalStateRoot(p, CPU).compute(set(), {}, {hk})
        assert root == naive_state_root(accounts, {})
        assert p.hashed_account(hk).storage_root == EMPTY_ROOT_HASH
