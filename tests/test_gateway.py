"""RPC serving gateway: admission control, coalescing, head-invalidated
response caching, fault drills, and one-gateway transport parity.

The acceptance bar (ISSUE 5): under >= 8 client threads issuing
duplicate reads the coalesce factor exceeds 1 with every response
bit-identical to the ungated path; full-queue shedding returns -32005
without wedging other classes; HTTP, WS, and IPC all route through ONE
gateway.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import socket
import struct
import threading
import time
import urllib.request

import pytest

from reth_tpu.metrics import MetricsRegistry
from reth_tpu.primitives.keccak import keccak256
from reth_tpu.rpc.gateway import (
    CLASSES,
    DEFAULT_COALESCE,
    OVERLOADED,
    GatewayFaultInjector,
    RpcGateway,
    classify,
)
from reth_tpu.rpc.server import RpcServer

# every gateway below gets its own registry: the global one would reject
# re-registration across tests (and cross-pollute counters)


def make_gateway(**kw):
    kw.setdefault("registry", MetricsRegistry())
    return RpcGateway(**kw)


def handle(server, method, params, rid=1):
    out = json.loads(server.handle(json.dumps(
        {"jsonrpc": "2.0", "id": rid, "method": method,
         "params": params}).encode()))
    return out


# -- classification -----------------------------------------------------------


def test_classification():
    assert classify("engine_newPayloadV4") == "engine"
    assert classify("engine_forkchoiceUpdatedV3") == "engine"
    assert classify("eth_sendRawTransaction") == "tx"
    assert classify("debug_traceTransaction") == "debug"
    assert classify("trace_block") == "debug"
    assert classify("ots_getApiLevel") == "debug"
    assert classify("eth_call") == "read"
    assert classify("eth_getLogs") == "read"
    assert classify("net_version") == "read"
    # producer introspection shares the leader-only engine lane; pending-tx
    # reads are replica-servable via the pt_* feed view
    assert classify("producer_status") == "engine"
    assert classify("txpool_content") == "read"
    assert classify("txpool_status") == "read"
    assert CLASSES.index("engine") < CLASSES.index("read") < \
        CLASSES.index("tx") < CLASSES.index("debug")
    # the cacheable set is exactly the pure head-scoped reads
    assert "eth_call" in DEFAULT_COALESCE
    assert "eth_sendRawTransaction" not in DEFAULT_COALESCE


def test_classification_fleet_admin_rides_engine_class():
    """fleet-admin / feed-control methods (replica registration,
    draining, ring status probes) must classify as engine so they can
    never starve in the 2-slot debug class behind a debug_traceBlock
    re-execution — a sick replica needs shedding exactly when the node
    is busiest."""
    for method in ("fleet_register", "fleet_deregister", "fleet_drain",
                   "fleet_status"):
        assert classify(method) == "engine", method
    # and they are control-plane: never coalesced or cached
    assert not any(m.startswith("fleet_") for m in DEFAULT_COALESCE)


# -- coalescing stress --------------------------------------------------------


def _deterministic_handler(executions, delay=0.003):
    """An eth_call-shaped handler: deterministic in its params, with a
    side execution counter NOT reflected in the result (so coalesced and
    uncoalesced responses can be compared byte-for-byte)."""

    def eth_call(*params):
        executions.append(threading.get_ident())
        time.sleep(delay)  # widen the in-flight window
        return {"data": "0x" + keccak256(
            json.dumps(params, sort_keys=True).encode()).hex()}

    return eth_call


def test_threaded_stress_coalesced_bit_identical():
    """8 client threads x duplicate reads: every gated response is
    bit-identical to the ungated server's, the handler runs far fewer
    times than the request count, and gateway_* metrics show
    coalesce factor > 1."""
    gw = make_gateway(head_supplier=lambda: b"head-1", cache_size=0)
    gated_execs, naive_execs = [], []
    gated = RpcServer(gateway=gw)
    gated.register_method("eth_call", _deterministic_handler(gated_execs))
    naive = RpcServer()
    naive.register_method("eth_call", _deterministic_handler(naive_execs))

    threads, rounds = 8, 10
    barrier = threading.Barrier(threads)
    results: dict[tuple, bytes] = {}
    errors: list = []

    def client(t):
        try:
            for r in range(rounds):
                barrier.wait()  # all threads fire the same key together
                params = [{"to": f"0x{r:040x}", "data": "0xdeadbeef"}, "latest"]
                body = json.dumps({"jsonrpc": "2.0", "id": 42,
                                   "method": "eth_call",
                                   "params": params}).encode()
                results[(t, r)] = gated.handle(body)
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    ts = [threading.Thread(target=client, args=(t,)) for t in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors, errors
    # bit-identical to the ungated path, and across all coalesced clients
    for r in range(rounds):
        params = [{"to": f"0x{r:040x}", "data": "0xdeadbeef"}, "latest"]
        body = json.dumps({"jsonrpc": "2.0", "id": 42, "method": "eth_call",
                           "params": params}).encode()
        want = naive.handle(body)
        for t in range(threads):
            assert results[(t, r)] == want
    total = threads * rounds
    assert len(gated_execs) < total, "no coalescing happened"
    assert gw.coalesce_factor() > 1.0
    assert gw.snapshot()["coalesced"] == total - len(gated_execs)
    # the metrics registry agrees with the snapshot
    text = gw.metrics._coalesce_factor.value
    assert text > 1.0


def test_coalesced_errors_fan_out():
    """A leader's failure propagates to every coalesced follower — no
    follower hangs or silently gets a default."""
    gw = make_gateway(cache_size=0)
    srv = RpcServer(gateway=gw)
    gate = threading.Event()

    def eth_call(*params):
        gate.wait(5)
        raise ValueError("boom")

    srv.register_method("eth_call", eth_call)
    outs = [None, None]

    def client(i):
        outs[i] = handle(srv, "eth_call", ["x"])

    ts = [threading.Thread(target=client, args=(i,)) for i in range(2)]
    ts[0].start()
    time.sleep(0.05)
    ts[1].start()
    time.sleep(0.05)
    gate.set()
    for t in ts:
        t.join()
    for out in outs:
        assert out["error"]["message"].endswith("boom")


# -- response cache + head invalidation ---------------------------------------


def test_cache_hits_and_head_invalidation():
    """Identical reads at one head execute once; a canonical-head change
    both re-keys and wholesale-clears the cache."""
    head = {"h": b"h1"}
    gw = make_gateway(head_supplier=lambda: head["h"])
    srv = RpcServer(gateway=gw)
    execs = []
    srv.register_method("eth_getLogs", _deterministic_handler(execs, delay=0))

    first = handle(srv, "eth_getLogs", [{"fromBlock": "0x1"}])
    again = handle(srv, "eth_getLogs", [{"fromBlock": "0x1"}])
    assert first["result"] == again["result"]
    assert len(execs) == 1
    assert gw.cache_hits == 1 and gw.cache_hit_rate() > 0
    # different params = different key
    handle(srv, "eth_getLogs", [{"fromBlock": "0x2"}])
    assert len(execs) == 2
    # head change: the canon-listener hook clears the cache wholesale
    head["h"] = b"h2"
    gw.on_head_change(chain=[])
    assert gw.invalidations == 1
    handle(srv, "eth_getLogs", [{"fromBlock": "0x1"}])
    assert len(execs) == 3
    # non-coalescable methods never touch the cache
    srv.register_method("eth_sendRawTransaction", lambda *a: "0x00")
    handle(srv, "eth_sendRawTransaction", ["0x01"])
    handle(srv, "eth_sendRawTransaction", ["0x01"])
    assert gw.cache_misses == 3  # unchanged by the tx submissions


def test_cache_bounded_lru():
    gw = make_gateway(head_supplier=lambda: b"h", cache_size=2)
    srv = RpcServer(gateway=gw)
    execs = []
    srv.register_method("eth_call", _deterministic_handler(execs, delay=0))
    for i in range(3):
        handle(srv, "eth_call", [f"k{i}"])
    handle(srv, "eth_call", ["k0"])  # evicted by k2 -> recompute
    assert len(execs) == 4


# -- admission: shedding, priority, aging -------------------------------------


def test_full_queue_sheds_without_wedging_other_classes():
    """One slow read + a full read queue: the next read sheds with
    -32005 (+ retry_after data) while engine traffic keeps flowing; the
    queued read completes once the slot frees."""
    gw = make_gateway(class_limits={"read": 1},
                      queue_caps={"read": 1}, cache_size=0)
    srv = RpcServer(gateway=gw)
    gate = threading.Event()
    srv.register_method("eth_slow", lambda: gate.wait(10) and None or "slow")
    srv.register_method("eth_fast", lambda: "fast")
    srv.register_method("engine_ping", lambda: "pong")

    outs = {}

    def call(name, method):
        outs[name] = handle(srv, method, [])

    t_run = threading.Thread(target=call, args=("running", "eth_slow"))
    t_run.start()
    time.sleep(0.05)  # running occupies the read slot
    t_q = threading.Thread(target=call, args=("queued", "eth_fast"))
    t_q.start()
    time.sleep(0.05)  # queued fills the read queue (cap 1)
    shed = handle(srv, "eth_fast", [])
    assert shed["error"]["code"] == OVERLOADED
    assert shed["error"]["data"]["retry_after"] > 0
    assert shed["error"]["data"]["class"] == "read"
    # other classes are NOT wedged by the full read lane
    assert handle(srv, "engine_ping", [])["result"] == "pong"
    assert gw.snapshot()["sheds"] == 1
    gate.set()
    t_run.join(5)
    t_q.join(5)
    assert outs["queued"]["result"] == "fast"
    assert not t_run.is_alive() and not t_q.is_alive()


def test_priority_and_antistarvation_aging():
    """With one global slot: a fresh engine request outranks a fresh
    debug request, but a debug waiter older than age_promote_s is
    granted FIRST (the hash-service aging rule on the serving path)."""
    gw = make_gateway(max_concurrent=1, age_promote_s=0.08, cache_size=0)
    srv = RpcServer(gateway=gw)
    order = []
    gate = threading.Event()
    srv.register_method("eth_block", lambda: gate.wait(10) or "done")
    srv.register_method("debug_probe", lambda: order.append("debug") or "d")
    srv.register_method("engine_probe", lambda: order.append("engine") or "e")

    t0 = threading.Thread(target=handle, args=(srv, "eth_block", []))
    t0.start()
    time.sleep(0.05)
    td = threading.Thread(target=handle, args=(srv, "debug_probe", []))
    td.start()
    time.sleep(0.12)  # debug waiter ages past age_promote_s
    te = threading.Thread(target=handle, args=(srv, "engine_probe", []))
    te.start()
    time.sleep(0.05)
    gate.set()
    for t in (t0, td, te):
        t.join(5)
    assert order == ["debug", "engine"]  # aged debug beat fresh engine


def test_fresh_priority_order():
    """Without aging, a waiting engine request is granted before a
    debug request that enqueued earlier."""
    gw = make_gateway(max_concurrent=1, age_promote_s=60.0, cache_size=0)
    srv = RpcServer(gateway=gw)
    order = []
    gate = threading.Event()
    srv.register_method("eth_block", lambda: gate.wait(10) or "done")
    srv.register_method("debug_probe", lambda: order.append("debug") or "d")
    srv.register_method("engine_probe", lambda: order.append("engine") or "e")

    t0 = threading.Thread(target=handle, args=(srv, "eth_block", []))
    t0.start()
    time.sleep(0.05)
    td = threading.Thread(target=handle, args=(srv, "debug_probe", []))
    td.start()
    time.sleep(0.05)
    te = threading.Thread(target=handle, args=(srv, "engine_probe", []))
    te.start()
    time.sleep(0.05)
    gate.set()
    for t in (t0, td, te):
        t.join(5)
    assert order == ["engine", "debug"]


# -- fault drills -------------------------------------------------------------


def test_fault_drill_shed_every():
    """RETH_TPU_FAULT_GATEWAY_SHED drills the client-visible -32005 path
    without real overload."""
    inj = GatewayFaultInjector(shed_every=3)
    gw = make_gateway(injector=inj, cache_size=0)
    srv = RpcServer(gateway=gw)
    srv.register_method("eth_ping", lambda: "pong")
    codes = []
    for i in range(6):
        out = handle(srv, "eth_ping", [])
        codes.append(out.get("error", {}).get("code"))
    assert codes == [None, None, OVERLOADED, None, None, OVERLOADED]
    assert inj.forced_sheds == 2
    assert gw.snapshot()["fault_injection"] is True


def test_fault_drill_stall_backs_up_queue():
    """RETH_TPU_FAULT_GATEWAY_STALL slows every execution, which backs
    concurrent requests up into the bounded queue (visible in the wait
    histogram and queue metrics)."""
    inj = GatewayFaultInjector(stall=0.05)
    gw = make_gateway(class_limits={"read": 1}, injector=inj, cache_size=0)
    srv = RpcServer(gateway=gw)
    srv.register_method("eth_ping", lambda: "pong")
    t0 = time.monotonic()
    ts = [threading.Thread(target=handle, args=(srv, "eth_ping", []))
          for _ in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(10)
    assert time.monotonic() - t0 >= 0.15  # serialized through the stall
    # the second/third requests waited for the read slot
    wait_hist = gw.metrics._wait["read"]
    assert wait_hist.n == 3 and wait_hist.total > 0.05


def test_injector_from_env():
    env = {"RETH_TPU_FAULT_GATEWAY_STALL": "0.5",
           "RETH_TPU_FAULT_GATEWAY_SHED": "7"}
    inj = GatewayFaultInjector.from_env(env)
    assert inj.stall == 0.5 and inj.shed_every == 7 and inj.active()
    assert GatewayFaultInjector.from_env({}) is None


# -- transport parity: HTTP, WS, IPC through ONE gateway ----------------------


def _ws_client(port):
    from reth_tpu.rpc.ws import _WS_GUID

    sock = socket.create_connection(("127.0.0.1", port), timeout=10)
    key = base64.b64encode(os.urandom(16))
    sock.sendall(
        b"GET / HTTP/1.1\r\nHost: x\r\nUpgrade: websocket\r\n"
        b"Connection: Upgrade\r\nSec-WebSocket-Key: " + key +
        b"\r\nSec-WebSocket-Version: 13\r\n\r\n"
    )
    resp = b""
    while b"\r\n\r\n" not in resp:
        resp += sock.recv(4096)
    assert b"101" in resp.split(b"\r\n")[0]
    assert base64.b64encode(hashlib.sha1(key + _WS_GUID).digest()) in resp
    return sock


def _ws_request(sock, payload: bytes) -> bytes:
    mask = os.urandom(4)
    header = bytes([0x80 | 1])
    n = len(payload)
    if n < 126:
        header += bytes([0x80 | n])
    else:
        header += bytes([0x80 | 126]) + struct.pack(">H", n)
    sock.sendall(header + mask
                 + bytes(c ^ mask[i % 4] for i, c in enumerate(payload)))
    b0, b1 = sock.recv(1)[0], sock.recv(1)[0]
    ln = b1 & 0x7F
    if ln == 126:
        (ln,) = struct.unpack(">H", sock.recv(2))
    buf = b""
    while len(buf) < ln:
        buf += sock.recv(ln - len(buf))
    return buf


def test_http_ws_ipc_route_through_one_gateway(tmp_path):
    """All three transports wrap one RpcServer registry, so one gateway
    observes (and caches/coalesces across) every transport: three
    identical reads over HTTP, WS, and IPC execute the handler ONCE and
    return identical results."""
    from reth_tpu.rpc.ipc import IpcRpcServer
    from reth_tpu.rpc.ws import WsRpcServer

    gw = make_gateway(head_supplier=lambda: b"h")
    srv = RpcServer(gateway=gw)
    execs = []
    srv.register_method("eth_call", _deterministic_handler(execs, delay=0))
    http_port = srv.start()
    ws = WsRpcServer(srv)
    ws_port = ws.start()
    ipc = IpcRpcServer(srv, tmp_path / "node.ipc")
    ipc_path = ipc.start()
    body = json.dumps({"jsonrpc": "2.0", "id": 1, "method": "eth_call",
                       "params": ["parity"]}).encode()
    try:
        http_out = urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{http_port}/", body,
            {"Content-Type": "application/json"}), timeout=10).read()
        wsock = _ws_client(ws_port)
        ws_out = _ws_request(wsock, body)
        wsock.close()
        isock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        isock.connect(ipc_path)
        isock.sendall(body + b"\n")
        ipc_out = b""
        while not ipc_out.endswith(b"\n"):
            ipc_out += isock.recv(4096)
        isock.close()
    finally:
        srv.stop()
        ws.stop()
        ipc.stop()
    assert json.loads(http_out) == json.loads(ws_out) == \
        json.loads(ipc_out.strip())
    assert len(execs) == 1, "transports did not share the gateway cache"
    assert gw.requests == 3
    assert gw.cache_hits == 2


# -- node-level e2e -----------------------------------------------------------


@pytest.fixture()
def gateway_node():
    from reth_tpu.node import Node, NodeConfig
    from reth_tpu.primitives import Account
    from reth_tpu.primitives.keccak import keccak256_batch_np
    from reth_tpu.testing import ChainBuilder, Wallet
    from reth_tpu.trie import TrieCommitter

    cpu = TrieCommitter(hasher=keccak256_batch_np)
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)},
                           committer=cpu)
    cfg = NodeConfig(dev=True, rpc_gateway=True,
                     genesis_header=builder.genesis,
                     genesis_alloc=builder.accounts_at_genesis)
    n = Node(cfg, committer=cpu)
    n.start_rpc()
    yield n, alice
    n.stop()


def rpc(port, method, *params):
    req = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                      "params": list(params)})
    out = json.loads(urllib.request.urlopen(urllib.request.Request(
        f"http://127.0.0.1:{port}/", req.encode(),
        {"Content-Type": "application/json"}), timeout=30).read())
    if "error" in out:
        raise RuntimeError(f"{method}: {out['error']}")
    return out["result"]


def test_node_gateway_e2e(gateway_node):
    """A live node with --rpc-gateway: duplicate reads hit the response
    cache, mining a block invalidates it via the canon listener, and the
    gateway_* series are on /metrics."""
    n, alice = gateway_node
    port = n.rpc.port
    assert n.gateway is not None and n.rpc.gateway is n.gateway
    assert n.authrpc.gateway is n.gateway  # one admission domain
    blk = rpc(port, "eth_getBlockByNumber", "0x0", False)
    blk2 = rpc(port, "eth_getBlockByNumber", "0x0", False)
    assert blk == blk2
    assert n.gateway.cache_hits >= 1
    inval_before = n.gateway.invalidations
    n.miner.mine_block(timestamp=1_900_000_000)
    assert n.gateway.invalidations > inval_before
    # post-head-change reads recompute against the new head
    assert rpc(port, "eth_blockNumber") == "0x1"
    metrics = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
    assert "gateway_requests_total_read" in metrics
    assert "gateway_cache_hits_total" in metrics
    # the events dashboard line carries the gateway fragment
    n.event_reporter.on_canon_change([])  # no-op intake
    snap = n.gateway.snapshot()
    assert snap["requests"] >= 3 and snap["cache_hits"] >= 1
