"""ExEx backfill + FinishedHeight pruning gate.

Reference analogue: crates/exex/exex/src/backfill/ (BackfillJob re-executes
historical ranges for late-registered extensions) and the FinishedHeight
contract (src/lib.rs:17-24): pruning must never outrun the slowest ExEx.
"""

from __future__ import annotations

import pytest

from reth_tpu.exex import BackfillJob, CanonStateNotification, ExExManager
from reth_tpu.node import Node, NodeConfig
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.prune import PruneMode, PruneModes
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)


def dev_node(tmp_path, **cfg_kw):
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)},
                           committer=CPU)
    cfg = NodeConfig(dev=True, datadir=tmp_path,
                     genesis_header=builder.genesis,
                     genesis_alloc=builder.accounts_at_genesis,
                     persistence_threshold=cfg_kw.pop("persistence_threshold", 0),
                     **cfg_kw)
    return Node(cfg, committer=CPU), alice


def test_backfill_reexecutes_history_with_outputs(tmp_path):
    """A late ExEx backfills a historical range: every chunk arrives with
    REAL re-executed outputs whose receipts match what the chain stored."""
    node, alice = dev_node(tmp_path)
    for i in range(6):
        node.pool.add_transaction(alice.transfer(b"\x0b" * 20, 100 + i))
        node.miner.mine_block()
    assert node.tree.persisted_number == 6

    seen = []
    handle = node.exex.register("indexer", lambda n: seen.append(n))
    delivered = node.exex.backfill(handle, node.factory, 1, 6,
                                   batch_blocks=2)
    assert delivered == 3  # 6 blocks in 2-block chunks
    assert [n.tip_number for n in seen] == [2, 4, 6]
    assert handle.finished_height == 6 and handle.backfilling is False
    # outputs are the real historical execution results
    with node.factory.provider() as p:
        for n in seen:
            for (num, _h), out in zip(n.blocks, n.outputs):
                idx = p.block_body_indices(num)
                for i, r in enumerate(out.receipts):
                    stored = p.receipt(idx.first_tx_num + i)
                    assert stored.cumulative_gas_used == r.cumulative_gas_used
    node.stop()


def test_backfill_interleaves_with_live_notifications(tmp_path):
    """Live tip notifications keep flowing to OTHER extensions while one
    handle backfills; the backfiller's finished_height lags at its own
    progress (it pins the pruning gate)."""
    # threshold 1 keeps the tip in memory so canonical notifications carry
    # the new block (a fully persisted chain has nothing left to announce)
    node, alice = dev_node(tmp_path, persistence_threshold=1)
    for i in range(4):
        node.pool.add_transaction(alice.transfer(b"\x0c" * 20, 50 + i))
        node.miner.mine_block()
    assert node.tree.persisted_number == 3

    live_seen = []
    node.exex.register("live", lambda n: live_seen.append(n.tip_number))
    slow_seen = []
    slow = node.exex.register("slow", lambda n: slow_seen.append(n.tip_number))

    # deliver one backfill chunk "mid-flight", then a live block lands
    job = iter(BackfillJob(node.factory, 1, 3, batch_blocks=2))
    slow.backfilling = True
    notification, outputs = next(job)
    slow.handler(notification)
    slow.finished_height = notification.tip_number
    assert node.exex.finished_height() == 0  # live handle hasn't seen any

    node.pool.add_transaction(alice.transfer(b"\x0c" * 20, 99))
    node.miner.mine_block()  # live notification -> both handlers
    assert live_seen[-1] == 5
    # the backfilling handle received the live notification but its
    # finished_height stays pinned at backfill progress
    assert slow_seen == [2, 5]
    assert slow.finished_height == 2
    assert node.exex.finished_height() == 2  # the gate
    node.stop()


def test_pruner_held_by_finished_height(tmp_path):
    """With receipts pruning configured, the pruner cannot advance past a
    backfilling ExEx's finished height; once the backfill completes and
    the height advances, pruning proceeds."""
    node, alice = dev_node(
        tmp_path, prune_modes=PruneModes(receipts=PruneMode(distance=1)))
    # an ExEx that is still at height 0 pins the gate
    handle = node.exex.register("holder", lambda n: None)
    handle.backfilling = True  # simulates a long backfill in progress
    for i in range(6):
        node.pool.add_transaction(alice.transfer(b"\x0d" * 20, 10 + i))
        node.miner.mine_block()
    with node.factory.provider() as p:
        idx = p.block_body_indices(1)
        assert p.receipt(idx.first_tx_num) is not None  # NOT pruned

    # backfill completes: the gate lifts, the next canonical change prunes
    node.exex.backfill(handle, node.factory, 1, node.tree.persisted_number)
    assert node.exex.finished_height() == node.tree.persisted_number
    node.pool.add_transaction(alice.transfer(b"\x0d" * 20, 999))
    node.miner.mine_block()
    with node.factory.provider() as p:
        idx = p.block_body_indices(1)
        assert p.receipt(idx.first_tx_num) is None  # pruned now
    node.stop()
