"""Cross-block import pipeline tests: speculate N+1 while N commits.

Differential guarantee: a pipelined import must produce receipts and
state roots bit-identical to a serial import of the same chain —
speculation only moves work earlier, adoption re-runs every consensus
check. Plus deterministic mid-commit speculation, the abort ladder
(invalid parent, fcU reorg), and lease hygiene.
"""

import random
import threading
import time

import pytest

from reth_tpu.engine import EngineTree
from reth_tpu.engine.block_pipeline import import_chain
from reth_tpu.engine.tree import PayloadStatusKind
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.primitives.types import Block, Header
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.storage.genesis import init_genesis
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)


def build_chain(n_blocks=6, n_wallets=8, txs_per_block=6, seed=7):
    """Random transfer chain with same-sender nonce chains and
    cross-block read-after-write (receivers of block i spend in i+1)."""
    rng = random.Random(seed)
    wallets = [Wallet(0x5EED + i) for i in range(n_wallets)]
    genesis = {w.address: Account(balance=10**21) for w in wallets}
    builder = ChainBuilder(genesis, committer=CPU)
    prev_receivers: list[int] = []
    for i in range(n_blocks):
        txs = []
        for j in range(txs_per_block):
            if prev_receivers and j < 2:
                # spend funds credited in the previous block: N+1 reads N's writes
                s = prev_receivers[j % len(prev_receivers)]
            else:
                s = rng.randrange(n_wallets)
            r = rng.randrange(n_wallets)
            txs.append(wallets[s].transfer(wallets[r].address, 10**14 + i * 100 + j))
            prev_receivers = [r] + prev_receivers[:1]
        # same-sender nonce chain inside the block
        s = rng.randrange(n_wallets)
        txs.append(wallets[s].transfer(wallets[(s + 1) % n_wallets].address, 10**13))
        txs.append(wallets[s].transfer(wallets[(s + 2) % n_wallets].address, 10**13))
        builder.build_block(txs)
    return builder


def fresh_tree(builder, depth=1, threshold=100):
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis, committer=CPU)
    return EngineTree(factory, committer=CPU, persistence_threshold=threshold,
                      pipeline_depth=depth)


def gate_commit(tree, n_gated=1):
    """Block the first n_gated commit legs (_sparse_root_or_fallback) on an
    event; return (reached, release). Instance-attr patch wins over the class
    method, so only this tree is affected."""
    reached = threading.Event()
    release = threading.Event()
    orig = tree._sparse_root_or_fallback
    calls = [0]

    def gated(*a, **kw):
        calls[0] += 1
        if calls[0] <= n_gated:
            reached.set()
            assert release.wait(timeout=30), "commit gate never released"
        return orig(*a, **kw)

    tree._sparse_root_or_fallback = gated
    return reached, release


# ---------------------------------------------------------------- differential


def test_pipelined_import_bit_identical_to_serial():
    builder = build_chain(n_blocks=5, n_wallets=6, txs_per_block=4, seed=11)
    t_serial = fresh_tree(builder, depth=1)
    t_piped = fresh_tree(builder, depth=2)

    st_s = import_chain(t_serial, builder.blocks[1:], fcu=False, overlap=False)
    st_p = import_chain(t_piped, builder.blocks[1:], fcu=False, overlap=True)

    assert all(s.status is PayloadStatusKind.VALID for s in st_s)
    assert all(s.status is PayloadStatusKind.VALID for s in st_p)
    for blk in builder.blocks[1:]:
        eb_s, eb_p = t_serial.blocks[blk.hash], t_piped.blocks[blk.hash]
        assert eb_s.block.header.state_root == eb_p.block.header.state_root
        assert eb_s.receipts == eb_p.receipts
        assert eb_s.senders == eb_p.senders
    stats = t_piped.pipeline.stats_snapshot()
    assert stats["adopted"] >= 1, stats
    assert stats["leases_active"] == 0


@pytest.mark.slow  # multi-seed sweep rides `make test-import-pipeline`; tier-1 keeps the single-seed differential above
@pytest.mark.parametrize("seed", [3, 23, 101])
def test_pipelined_import_randomized_seeds(seed):
    builder = build_chain(n_blocks=5, n_wallets=6, txs_per_block=4, seed=seed)
    t_serial = fresh_tree(builder, depth=1)
    t_piped = fresh_tree(builder, depth=2)
    import_chain(t_serial, builder.blocks[1:], fcu=False, overlap=False)
    import_chain(t_piped, builder.blocks[1:], fcu=False, overlap=True)
    tip = builder.blocks[-1].hash
    assert tip in t_serial.blocks and tip in t_piped.blocks
    assert (t_serial.blocks[tip].block.header.state_root
            == t_piped.blocks[tip].block.header.state_root)
    assert t_piped.pipeline.stats_snapshot()["leases_active"] == 0


def test_import_chain_with_fcu_advances_head():
    builder = build_chain(n_blocks=3, n_wallets=6, txs_per_block=3, seed=5)
    tree = fresh_tree(builder, depth=2, threshold=2)
    sts = import_chain(tree, builder.blocks[1:], fcu=True, overlap=True)
    assert all(s.status is PayloadStatusKind.VALID for s in sts)
    assert tree.head_hash == builder.blocks[-1].hash


# ------------------------------------------------------------- deterministic


def test_speculation_runs_while_parent_mid_commit():
    builder = build_chain(n_blocks=2, seed=9)
    tree = fresh_tree(builder, depth=2)
    b1, b2 = builder.blocks[1], builder.blocks[2]
    reached, release = gate_commit(tree, n_gated=1)

    t = threading.Thread(target=tree.on_new_payload, args=(b1,))
    t.start()
    assert reached.wait(timeout=30)
    # b1 is now held mid-commit; its window is open, so b2 must speculate
    assert tree.pipeline.wait_commit_open(b1.hash, timeout=10)

    done = {}

    def submit():
        done["st"] = tree.on_new_payload(b2)

    t2 = threading.Thread(target=submit)
    t2.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if tree.pipeline.stats_snapshot()["speculations"] >= 1:
            break
        time.sleep(0.01)
    assert tree.pipeline.stats_snapshot()["speculations"] == 1
    release.set()
    t.join(timeout=30)
    t2.join(timeout=30)
    assert done["st"].status is PayloadStatusKind.VALID
    stats = tree.pipeline.stats_snapshot()
    assert stats["adopted"] == 1
    assert stats["aborted"] == 0
    assert stats["leases_active"] == 0
    assert b1.hash in tree.blocks and b2.hash in tree.blocks


def test_speculation_aborts_when_parent_invalid():
    builder = build_chain(n_blocks=2, seed=13)
    tree = fresh_tree(builder, depth=2)
    b1, b2 = builder.blocks[1], builder.blocks[2]
    bad1 = Block(Header(**{**b1.header.__dict__, "state_root": b"\x66" * 32}),
                 b1.transactions, (), b1.withdrawals)
    child = Block(Header(**{**b2.header.__dict__, "parent_hash": bad1.hash}),
                  b2.transactions, (), b2.withdrawals)

    reached, release = gate_commit(tree, n_gated=1)
    res = {}
    t = threading.Thread(target=lambda: res.setdefault("p", tree.on_new_payload(bad1)))
    t.start()
    assert reached.wait(timeout=30)
    assert tree.pipeline.wait_commit_open(bad1.hash, timeout=10)

    t2 = threading.Thread(target=lambda: res.setdefault("c", tree.on_new_payload(child)))
    t2.start()
    time.sleep(0.05)  # let the speculation start
    release.set()
    t.join(timeout=30)
    t2.join(timeout=30)

    assert res["p"].status is PayloadStatusKind.INVALID
    assert "state root mismatch" in res["p"].validation_error
    # the child must never be adopted off a failed parent
    assert res["c"].status in (PayloadStatusKind.INVALID, PayloadStatusKind.SYNCING)
    assert child.hash not in tree.blocks
    stats = tree.pipeline.stats_snapshot()
    assert stats["adopted"] == 0
    assert stats["leases_active"] == 0


def test_fcu_reorg_cancels_speculation():
    builder = build_chain(n_blocks=2, seed=17)
    # a competing fork block off genesis
    fork_builder = build_chain(n_blocks=1, seed=99)
    tree = fresh_tree(builder, depth=2)
    b1, b2 = builder.blocks[1], builder.blocks[2]
    fork = fork_builder.blocks[1]
    # fork chains share the wallet set but differ in txs => different hash
    assert fork.hash != b1.hash

    reached, release = gate_commit(tree, n_gated=1)
    res = {}
    t = threading.Thread(target=lambda: res.setdefault("p", tree.on_new_payload(b1)))
    t.start()
    assert reached.wait(timeout=30)
    assert tree.pipeline.wait_commit_open(b1.hash, timeout=10)

    t2 = threading.Thread(target=lambda: res.setdefault("c", tree.on_new_payload(b2)))
    t2.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if tree.pipeline.stats_snapshot()["speculations"] >= 1:
            break
        time.sleep(0.005)
    # reorg the head away from the speculation's lineage mid-flight
    tree.pipeline.on_forkchoice(fork.hash)
    release.set()
    t.join(timeout=30)
    t2.join(timeout=30)

    assert res["p"].status is PayloadStatusKind.VALID
    stats = tree.pipeline.stats_snapshot()
    if stats["speculations"]:
        assert stats["aborted"] >= 1 or stats["adopted"] >= 0
    assert stats["leases_active"] == 0
    # chain still importable after the abort
    if res["c"].status is not PayloadStatusKind.VALID:
        st = tree.on_new_payload(b2)
        assert st.status is PayloadStatusKind.VALID


# ----------------------------------------------------------------- plumbing


def test_depth_one_has_no_pipeline():
    builder = build_chain(n_blocks=1, seed=1)
    tree = fresh_tree(builder, depth=1)
    assert tree.pipeline is None
    st = tree.on_new_payload(builder.blocks[1])
    assert st.status is PayloadStatusKind.VALID


def test_env_var_enables_pipeline(monkeypatch):
    monkeypatch.setenv("RETH_TPU_PIPELINE_DEPTH", "2")
    builder = build_chain(n_blocks=1, seed=1)
    tree = fresh_tree(builder, depth=None)
    assert tree.pipeline is not None
    assert tree.pipeline.depth == 2


def test_close_commit_idempotent():
    builder = build_chain(n_blocks=1, seed=2)
    tree = fresh_tree(builder, depth=2)
    st = tree.on_new_payload(builder.blocks[1])
    assert st.status is PayloadStatusKind.VALID
    stats = tree.pipeline.stats_snapshot()
    assert stats["leases_active"] == 0


def test_serial_overlap_false_matches_overlap_true():
    """import_chain(overlap=False) on a depth-2 tree must also work."""
    builder = build_chain(n_blocks=2, n_wallets=6, txs_per_block=3, seed=21)
    tree = fresh_tree(builder, depth=2)
    sts = import_chain(tree, builder.blocks[1:], fcu=False, overlap=False)
    assert all(s.status is PayloadStatusKind.VALID for s in sts)
