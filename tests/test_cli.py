"""CLI tests: init / import / db stats / stage run via the real argv entry."""

import json

import pytest

from reth_tpu.cli import main
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)


@pytest.fixture()
def chain_files(tmp_path):
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)}, committer=CPU)
    for i in range(3):
        builder.build_block([alice.transfer(b"\x0b" * 20, 1000 + i)])
    genesis = {
        "config": {"chainId": 1},
        "gasLimit": hex(builder.genesis.gas_limit),
        "baseFeePerGas": hex(builder.genesis.base_fee_per_gas),
        "alloc": {
            "0x" + alice.address.hex(): {"balance": hex(10**21)},
        },
    }
    gpath = tmp_path / "genesis.json"
    gpath.write_text(json.dumps(genesis))
    cpath = tmp_path / "chain.rlp"
    cpath.write_bytes(builder.export_rlp())
    return tmp_path, gpath, cpath, builder


def test_init_and_db_stats(chain_files, capsys):
    tmp, gpath, cpath, builder = chain_files
    datadir = tmp / "data1"
    datadir.mkdir()
    assert main(["init", "--datadir", str(datadir), "--genesis", str(gpath), "--hasher", "cpu"]) == 0
    out = capsys.readouterr().out
    assert builder.genesis.hash.hex() in out
    assert main(["db", "stats", "--datadir", str(datadir)]) == 0
    out = capsys.readouterr().out
    assert "PlainAccountState" in out


def test_import_pipeline_and_stage_rerun(chain_files, capsys):
    tmp, gpath, cpath, builder = chain_files
    datadir = tmp / "data2"
    datadir.mkdir()
    assert main(["import", "--datadir", str(datadir), "--genesis", str(gpath),
                 "--hasher", "cpu", str(cpath)]) == 0
    out = capsys.readouterr().out
    assert "imported 3 blocks" in out and "pipeline synced to 3" in out
    # stage run is a no-op now but must succeed against the same datadir
    assert main(["stage", "run", "--datadir", str(datadir), "--stage", "all",
                 "--hasher", "cpu"]) == 0


def test_db_verify_trie(chain_files, capsys):
    tmp, gpath, cpath, builder = chain_files
    datadir = tmp / "data_verify"
    datadir.mkdir()
    main(["import", "--datadir", str(datadir), "--genesis", str(gpath),
          "--hasher", "cpu", str(cpath)])
    capsys.readouterr()
    assert main(["db", "verify-trie", "--datadir", str(datadir),
                 "--hasher", "cpu"]) == 0
    assert "trie OK at block 3" in capsys.readouterr().out
    # corrupt a hashed account -> mismatch detected (the default engine
    # is the paged COW B+tree: open the same pageddb the import wrote)
    from reth_tpu.storage import ProviderFactory
    from reth_tpu.storage.native import PagedDb
    from reth_tpu.primitives import Account

    factory = ProviderFactory(PagedDb(datadir / "pageddb"))
    with factory.provider_rw() as p:
        p.put_hashed_account(b"\x42" * 32, Account(balance=1))
    factory.db.flush()
    assert main(["db", "verify-trie", "--datadir", str(datadir),
                 "--hasher", "cpu"]) == 1
    err = capsys.readouterr().err
    assert "TRIE MISMATCH" in err and "missing stored branch" not in err or err

    # corrupt a stored branch node -> structural problem reported
    from reth_tpu.trie.committer import BranchNode

    factory2 = ProviderFactory(PagedDb(datadir / "pageddb"))
    with factory2.provider_rw() as p:
        p.put_account_branch(b"\x0a\x0b", BranchNode(0b11, 0, 0b1, (b"\x99" * 32,)))
    factory2.db.flush()
    assert main(["db", "verify-trie", "--datadir", str(datadir),
                 "--hasher", "cpu"]) == 1
    assert "extra stored branch" in capsys.readouterr().err


def test_genesis_mismatch_cli(chain_files, tmp_path):
    tmp, gpath, cpath, builder = chain_files
    datadir = tmp / "data3"
    datadir.mkdir()
    main(["init", "--datadir", str(datadir), "--genesis", str(gpath), "--hasher", "cpu"])
    # re-init with a different genesis must fail loudly
    other = json.loads(gpath.read_text())
    other["alloc"] = {}
    g2 = tmp_path / "g2.json"
    g2.write_text(json.dumps(other))
    from reth_tpu.storage.genesis import GenesisMismatch

    with pytest.raises(GenesisMismatch):
        main(["init", "--datadir", str(datadir), "--genesis", str(g2), "--hasher", "cpu"])


def test_dump_genesis(capsys):
    assert main(["dump-genesis"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["config"]["chainId"] == 1337
    assert out["alloc"]


def test_re_execute_matches(chain_files, capsys):
    tmp, gpath, cpath, builder = chain_files
    datadir = tmp / "data3"
    datadir.mkdir()
    assert main(["import", "--datadir", str(datadir), "--genesis", str(gpath),
                 "--hasher", "cpu", str(cpath)]) == 0
    capsys.readouterr()
    assert main(["re-execute", "--datadir", str(datadir)]) == 0
    out = capsys.readouterr().out
    assert "re-executed 3 blocks: all match" in out


def test_prune_command(chain_files, tmp_path, capsys):
    tmp, gpath, cpath, builder = chain_files
    datadir = tmp / "data4"
    datadir.mkdir()
    assert main(["import", "--datadir", str(datadir), "--genesis", str(gpath),
                 "--hasher", "cpu", str(cpath)]) == 0
    cfg = tmp_path / "reth.toml"
    cfg.write_text("[prune.sender_recovery]\ndistance = 0\n")
    capsys.readouterr()
    assert main(["prune", "--datadir", str(datadir), "--config", str(cfg)]) == 0
    out = capsys.readouterr().out
    assert "SenderRecovery" in out and "2 entries pruned" in out


def test_p2p_command(chain_files, capsys):
    pytest.importorskip("cryptography")  # live RLPx handshake needs AES
    from reth_tpu.consensus import EthBeaconConsensus
    from reth_tpu.net import NetworkManager, Status
    from reth_tpu.stages import Pipeline, default_stages
    from reth_tpu.storage import MemDb, ProviderFactory
    from reth_tpu.storage.genesis import import_chain, init_genesis

    tmp, gpath, cpath, builder = chain_files
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis, committer=CPU)
    import_chain(factory, builder.blocks[1:], EthBeaconConsensus(CPU))
    Pipeline(factory, default_stages(committer=CPU)).run(3)
    status = Status(network_id=1, head=builder.tip.hash,
                    genesis=builder.genesis.hash)
    server = NetworkManager(factory, status, node_priv=0xBEEF)
    server.start()
    try:
        assert main(["p2p", "header", "2", "--enode", server.enode,
                     "--genesis-hash", "0x" + builder.genesis.hash.hex()]) == 0
        out = capsys.readouterr().out
        assert f"hash=0x{builder.blocks[2].hash.hex()}" in out
        assert main(["p2p", "body", "0x" + builder.blocks[2].hash.hex(),
                     "--enode", server.enode,
                     "--genesis-hash", "0x" + builder.genesis.hash.hex()]) == 0
        out = capsys.readouterr().out
        assert "transactions=1" in out
    finally:
        server.stop()


def test_node_native_db_backend(chain_files, tmp_path):
    """--db native runs the node on the C++ WAL engine end to end."""
    from reth_tpu.node import Node, NodeConfig

    tmp, gpath, cpath, builder = chain_files
    datadir = tmp_path / "native_data"
    datadir.mkdir()
    alice = Wallet(0xA11CE)
    cfg = NodeConfig(dev=True, datadir=str(datadir), db_backend="native",
                     genesis_header=builder.genesis,
                     genesis_alloc=builder.accounts_at_genesis)
    n = Node(cfg, committer=CPU)
    try:
        tx = alice.transfer(b"\x0b" * 20, 42)
        n.pool.add_transaction(tx)
        n.miner.mine_block()
        with n.factory.provider() as p:
            assert p.last_block_number() >= 0
        assert type(n.factory.db).__name__ == "NativeDb"
    finally:
        n.stop()


def test_db_get_list_diff_repair(chain_files, capsys):
    tmp_path, gpath, cpath, builder = chain_files
    datadir = tmp_path / "d"
    datadir.mkdir()
    main(["import", "--datadir", str(datadir), "--genesis", str(gpath),
          "--hasher", "cpu", str(cpath)])
    capsys.readouterr()
    # list + get round-trip through the real argv entry
    assert main(["db", "list", "--datadir", str(datadir),
                 "PlainAccountState", "--limit", "5"]) == 0
    out = capsys.readouterr().out
    key = out.split()[0]
    assert key.startswith("0x")
    assert main(["db", "get", "--datadir", str(datadir),
                 "PlainAccountState", key]) == 0
    assert capsys.readouterr().out.startswith("0x")
    # identical copy: diff clean
    import shutil

    shutil.copytree(datadir, tmp_path / "d2")
    assert main(["db", "diff", "--datadir", str(datadir),
                 str(tmp_path / "d2")]) == 0
    assert "0 difference(s)" in capsys.readouterr().out
    # corrupt a trie node, repair restores the root
    from reth_tpu.storage.native import PagedDb
    from reth_tpu.storage.tables import Tables

    db = PagedDb(datadir / "pageddb")
    with db.tx_mut() as tx:
        entry = tx.cursor(Tables.AccountsTrie.name).first()
        tx.put(Tables.AccountsTrie.name, entry[0], b"\x00garbage")
    db.flush()
    assert main(["db", "diff", "--datadir", str(datadir),
                 str(tmp_path / "d2")]) == 1
    capsys.readouterr()
    assert main(["db", "repair-trie", "--datadir", str(datadir),
                 "--hasher", "cpu"]) == 0
    assert "repaired" in capsys.readouterr().out
    assert main(["db", "verify-trie", "--datadir", str(datadir),
                 "--hasher", "cpu"]) == 0


def test_init_state_and_config_and_vectors(tmp_path, capsys):
    from reth_tpu.primitives.types import Header
    from reth_tpu.trie.state_root import state_root

    root, _ = state_root({b"\xcd" * 20: Account(nonce=1, balance=5)}, {},
                         committer=CPU)
    h = Header(number=9, state_root=root)
    dump = {"header": "0x" + h.encode().hex(),
            "accounts": {"0x" + "cd" * 20: {"balance": "0x5", "nonce": "0x1"}}}
    spath = tmp_path / "state.json"
    spath.write_text(json.dumps(dump))
    assert main(["init-state", str(spath), "--datadir", str(tmp_path / "s"),
                 "--hasher", "cpu"]) == 0
    assert "block 9" in capsys.readouterr().out
    assert main(["db", "verify-trie", "--datadir", str(tmp_path / "s"),
                 "--hasher", "cpu"]) == 0
    capsys.readouterr()
    assert main(["test-vectors", "--count", "3"]) == 0
    vecs = json.loads(capsys.readouterr().out)
    assert len(vecs["accounts"]) == 3
    assert main(["config"]) == 0
    assert "[stages.merkle]" in capsys.readouterr().out


def test_legacy_memdb_datadir_keeps_its_engine(chain_files, capsys):
    """A datadir initialised under --db memdb must keep opening memdb when
    --db is unset — the paged default must never silently serve a fresh
    empty store over existing data."""
    tmp, gpath, cpath, builder = chain_files
    datadir = tmp / "legacy"
    datadir.mkdir()
    assert main(["import", "--datadir", str(datadir), "--genesis", str(gpath),
                 "--hasher", "cpu", "--db", "memdb", str(cpath)]) == 0
    capsys.readouterr()
    # no --db: resolution must find db.bin and read the imported chain
    assert main(["db", "stats", "--datadir", str(datadir)]) == 0
    out = capsys.readouterr().out
    assert "CanonicalHeaders" in out and not (datadir / "pageddb").exists()


def test_node_explicit_paged_requires_datadir(capsys):
    assert main(["node", "--dev", "--db", "paged"]) == 1
    assert "needs --datadir" in capsys.readouterr().err


def test_stale_empty_store_does_not_mask_initialised_one(chain_files, capsys):
    """An auto-created EMPTY pageddb (left behind by a command run before
    init) must not win backend resolution over a later-initialised memdb
    (round-4 review finding)."""
    tmp, gpath, cpath, builder = chain_files
    datadir = tmp / "stale"
    datadir.mkdir()
    # any offline command against the uninitialised dir creates pageddb/
    main(["db", "stats", "--datadir", str(datadir)])
    assert (datadir / "pageddb").exists()
    capsys.readouterr()
    assert main(["import", "--datadir", str(datadir), "--genesis", str(gpath),
                 "--hasher", "cpu", "--db", "memdb", str(cpath)]) == 0
    capsys.readouterr()
    assert main(["db", "stats", "--datadir", str(datadir)]) == 0
    out = capsys.readouterr().out
    # resolution must pick the written memdb, which holds the chain
    assert "CanonicalHeaders" in out
    assert any(line.split() == ["Transactions", "3"]
               for line in out.splitlines())


def test_hash_service_flag_wires_committer(chain_files, capsys):
    """--hash-service: the committer grows a HashService whose live-lane
    client becomes its hasher; init + verify-trie run end-to-end through
    the service and the config dump carries the knob."""
    from reth_tpu.cli import _make_committer
    from reth_tpu.ops.hash_service import HashClient, HashService

    class _Args:
        hasher = "cpu"
        hash_service = True

    committer = _make_committer(_Args())
    try:
        assert isinstance(committer.hash_service, HashService)
        assert isinstance(committer.hasher, HashClient)
        assert committer.hasher.lane == "live"
        assert committer.for_lane("proof").hasher.lane == "proof"
        # digests are the service's, bit-identical to the direct path
        assert committer.hasher([b"abc"]) == keccak256_batch_np([b"abc"])
        assert committer.hash_service.dispatches >= 1
    finally:
        committer.hash_service.stop()

    tmp, gpath, cpath, builder = chain_files
    datadir = tmp / "svc"
    assert main(["init", "--datadir", str(datadir), "--genesis", str(gpath),
                 "--hasher", "cpu", "--hash-service"]) == 0
    assert main(["db", "verify-trie", "--datadir", str(datadir),
                 "--hasher", "cpu", "--hash-service"]) == 0
    capsys.readouterr()
    assert main(["config"]) == 0
    assert "hash_service = false" in capsys.readouterr().out
