"""Precompiles 6-10: bn254 add/mul/pairing, blake2f, KZG point evaluation.

Pairing correctness rests on property tests (bilinearity + non-degeneracy
+ mu_r membership): every non-degenerate bilinear pairing into mu_r is a
fixed power of every other, so EIP-197 product checks and KZG equality
checks are invariant across pairing choices (see primitives/pairing.py).
blake2f is pinned to the EIP-152 spec vectors.
"""

from __future__ import annotations

import hashlib

import pytest

from reth_tpu.evm.interpreter import (
    _pre_blake2f,
    _pre_bn_add,
    _pre_bn_mul,
    _pre_bn_pairing,
    _pre_point_eval,
)
from reth_tpu.primitives import kzg
from reth_tpu.primitives.pairing import (
    BLS12_381,
    BN254,
    f12_one,
    f12_pow,
    g1_group,
    g2_group,
    g2_valid,
    pairing,
    pairing_product_is_one,
)

GAS = 10**7


def _enc(*ints: int) -> bytes:
    return b"".join(i.to_bytes(32, "big") for i in ints)


# -- pairing properties ------------------------------------------------------


@pytest.mark.parametrize("curve", [BN254, BLS12_381], ids=lambda c: c.name)
def test_pairing_properties(curve):
    g1, g2 = g1_group(curve), g2_group(curve)
    assert g1.on_curve(curve.g1) and g2.on_curve(curve.g2)
    assert g1.mul_scalar(curve.g1, curve.r) is None
    assert g2.mul_scalar(curve.g2, curve.r) is None
    e = pairing(curve.g1, curve.g2, curve)
    assert e != f12_one(curve)                      # non-degenerate
    assert f12_pow(e, curve.r, curve) == f12_one(curve)  # in mu_r
    a, b = 1234567, 89101112
    eab = pairing(g1.mul_scalar(curve.g1, a), g2.mul_scalar(curve.g2, b), curve)
    assert eab == f12_pow(e, a * b, curve)          # bilinear
    neg = (curve.g1[0], (-curve.g1[1]) % curve.p)
    assert pairing_product_is_one([(curve.g1, curve.g2), (neg, curve.g2)], curve)


# -- 0x06 / 0x07: bn254 add / mul -------------------------------------------


def test_bn_add_known_double():
    # 2 * (1, 2) — the canonical EIP-196 doubling result
    ok, _, out = _pre_bn_add(_enc(1, 2, 1, 2), GAS)
    assert ok
    assert int.from_bytes(out[:32], "big") == (
        1368015179489954701390400359078579693043519447331113978918064868415326638035
    )
    assert int.from_bytes(out[32:], "big") == (
        9918110051302171585080402603319702774565515993150576347155970296011118125764
    )


def test_bn_add_identity_and_inverse():
    ok, _, out = _pre_bn_add(_enc(1, 2, 0, 0), GAS)
    assert ok and out == _enc(1, 2)
    ok, _, out = _pre_bn_add(_enc(1, 2, 1, BN254.p - 2), GAS)
    assert ok and out == _enc(0, 0)


def test_bn_mul_matches_repeated_add():
    ok, _, out = _pre_bn_mul(_enc(1, 2, 9), GAS)
    assert ok
    acc = b"\x00" * 64
    for _ in range(9):
        ok2, _, acc = _pre_bn_add(acc + _enc(1, 2), GAS)
        assert ok2
    assert out == acc


def test_bn_bad_point_rejected():
    ok, _, _ = _pre_bn_add(_enc(1, 3, 0, 0), GAS)
    assert not ok
    ok, _, _ = _pre_bn_mul(_enc(BN254.p, 2, 5), GAS)
    assert not ok


def test_bn_add_short_input_padded():
    ok, _, out = _pre_bn_add(_enc(1, 2), GAS)  # second point implied zero
    assert ok and out == _enc(1, 2)


# -- 0x08: pairing check ------------------------------------------------------


def _g2_words(q) -> bytes:
    (x0, x1), (y0, y1) = q
    return _enc(x1, x0, y1, y0)  # imaginary part first on the ABI


def test_bn_pairing_inverse_pair_is_one():
    neg = (1, BN254.p - 2)
    data = _enc(1, 2) + _g2_words(BN254.g2) + _enc(*neg) + _g2_words(BN254.g2)
    ok, _, out = _pre_bn_pairing(data, GAS)
    assert ok and int.from_bytes(out, "big") == 1


def test_bn_pairing_bilinear_cross():
    # e(2P, Q) * e(-P, 2Q)... != 1 ; e(2P, Q) * e(-2P, Q) == 1
    g1, g2 = g1_group(BN254), g2_group(BN254)
    p2 = g1.mul_scalar(BN254.g1, 2)
    np2 = (p2[0], BN254.p - p2[1])
    data = _enc(*p2) + _g2_words(BN254.g2) + _enc(*np2) + _g2_words(BN254.g2)
    ok, _, out = _pre_bn_pairing(data, GAS)
    assert ok and int.from_bytes(out, "big") == 1
    # e(2P, Q) * e(-P, Q) = e(P, Q) != 1
    neg = (1, BN254.p - 2)
    data = _enc(*p2) + _g2_words(BN254.g2) + _enc(*neg) + _g2_words(BN254.g2)
    ok, _, out = _pre_bn_pairing(data, GAS)
    assert ok and int.from_bytes(out, "big") == 0


def test_bn_pairing_empty_and_zero_points():
    ok, _, out = _pre_bn_pairing(b"", GAS)
    assert ok and int.from_bytes(out, "big") == 1
    data = _enc(0, 0) + _g2_words(BN254.g2)
    ok, _, out = _pre_bn_pairing(data, GAS)
    assert ok and int.from_bytes(out, "big") == 1


def test_bn_pairing_bad_length_or_subgroup():
    ok, _, _ = _pre_bn_pairing(b"\x00" * 191, GAS)
    assert not ok
    # a twist-curve point NOT in the r-torsion must be rejected
    g2 = g2_group(BN254)
    # find an off-subgroup point: on-curve x with y from sqrt... construct by
    # scaling the cofactor away is hard here; use an x/y that satisfies the
    # twist equation for a small multiple of a non-subgroup solution instead:
    # simplest reliable negative: corrupt one coordinate of a valid point.
    (x0, x1), (y0, y1) = BN254.g2
    bad = _enc(1, 2) + _enc(x1, x0, y1, (y0 + 1) % BN254.p)
    ok, _, _ = _pre_bn_pairing(bad, GAS)
    assert not ok


# -- 0x09: blake2f (EIP-152 spec vectors) ------------------------------------


_B2_BASE = (
    "48c9bdf267e6096a3ba7ca8485ae67bb2bf894fe72f36e3cf1361d5f3af54fa5"
    "d182e6ad7f520e511f6c3e2b8c68059b6bbd41fbabd9831f79217e1319cde05b"
    "6162630000000000000000000000000000000000000000000000000000000000"
    "0000000000000000000000000000000000000000000000000000000000000000"
    "0000000000000000000000000000000000000000000000000000000000000000"
    "0000000000000000000000000000000000000000000000000000000000000000"
    "0300000000000000" "0000000000000000" "01"
)


def test_blake2f_eip152_vector_12_rounds():
    data = bytes.fromhex("0000000c" + _B2_BASE)
    ok, gas_left, out = _pre_blake2f(data, GAS)
    assert ok and gas_left == GAS - 12
    assert out.hex() == (
        "ba80a53f981c4d0d6a2797b69f12f6e94c212f14685ac4b74b12bb6fdbffa2d1"
        "7d87c5392aab792dc252d5de4533cc9518d38aa8dbf1925ab92386edd4009923"
    )
    # and it must equal stdlib blake2b for the same message
    assert out == hashlib.blake2b(b"abc", digest_size=64).digest()


def test_blake2f_zero_rounds_and_bad_input():
    data = bytes.fromhex("00000000" + _B2_BASE)
    ok, gas_left, out = _pre_blake2f(data, GAS)
    assert ok and gas_left == GAS and len(out) == 64
    ok, _, _ = _pre_blake2f(data[:-1], GAS)          # 212 bytes
    assert not ok
    ok, _, _ = _pre_blake2f(data[:-1] + b"\x02", GAS)  # bad final flag
    assert not ok


# -- 0x0a: KZG point evaluation ----------------------------------------------


def _point_eval_input(coeffs, z, y=None, proof=None, vh=None):
    true_y, true_proof = kzg.prove_monomial(coeffs, z)
    commitment = kzg.commit_monomial(coeffs)
    cb = kzg.g1_to_bytes(commitment)
    pb = kzg.g1_to_bytes(proof if proof is not None else true_proof)
    return (
        (vh if vh is not None else kzg.kzg_to_versioned_hash(cb))
        + z.to_bytes(32, "big")
        + (y if y is not None else true_y).to_bytes(32, "big")
        + cb
        + pb
    )


def test_point_eval_valid_proof():
    coeffs = [7, 11, 13, 17]  # p(X) = 7 + 11X + 13X^2 + 17X^3
    data = _point_eval_input(coeffs, z=12345)
    ok, gas_left, out = _pre_point_eval(data, GAS)
    assert ok, "valid KZG proof rejected"
    assert gas_left == GAS - 50000
    assert int.from_bytes(out[:32], "big") == kzg.FIELD_ELEMENTS_PER_BLOB
    assert int.from_bytes(out[32:], "big") == kzg.BLS_MODULUS


def test_point_eval_wrong_y_rejected():
    coeffs = [7, 11, 13, 17]
    true_y, _ = kzg.prove_monomial(coeffs, 12345)
    data = _point_eval_input(coeffs, z=12345, y=(true_y + 1) % kzg.BLS_MODULUS)
    ok, _, _ = _pre_point_eval(data, GAS)
    assert not ok


def test_point_eval_wrong_versioned_hash_rejected():
    data = _point_eval_input([3, 5], z=9, vh=b"\x01" + b"\x00" * 31)
    ok, _, _ = _pre_point_eval(data, GAS)
    assert not ok


def test_point_eval_bad_length_rejected():
    ok, _, _ = _pre_point_eval(b"\x00" * 191, GAS)
    assert not ok


def test_g1_serialization_roundtrip():
    g1 = g1_group(BLS12_381)
    for k in (1, 2, 3, 7777):
        pt = g1.mul_scalar(BLS12_381.g1, k)
        assert kzg.g1_from_bytes(kzg.g1_to_bytes(pt)) == pt
    assert kzg.g1_from_bytes(kzg.g1_to_bytes(None)) is None


def test_g2_serialization_parses_generator_compressed():
    from reth_tpu.primitives.kzg import g2_from_bytes

    # compress the generator by hand: c1 || c0 with flag bits on c1
    (x0, x1), (y0, y1) = BLS12_381.g2
    is_largest = (y1 > (BLS12_381.p - 1) // 2) or (
        y1 == 0 and y0 > (BLS12_381.p - 1) // 2
    )
    raw = x1.to_bytes(48, "big") + x0.to_bytes(48, "big")
    flags = 0x80 | (0x20 if is_largest else 0)
    data = bytes([raw[0] | flags]) + raw[1:]
    assert g2_from_bytes(data) == BLS12_381.g2


def test_precompile_cache_hits_and_correctness():
    """Repeated identical precompile calls serve from the cache with the
    same output and gas (reference precompile_cache.rs); low-gas calls
    fail identically whether cached or not."""
    from reth_tpu.evm.interpreter import (
        _PRECOMPILE_CACHE,
        _PRECOMPILES,
        precompile_cache_stats,
    )

    _PRECOMPILE_CACHE.clear()
    before = dict(precompile_cache_stats)
    # bn254 add of two generator points, twice
    from reth_tpu.primitives.pairing import BN254

    gx, gy = BN254.g1
    data = (gx.to_bytes(32, "big") + gy.to_bytes(32, "big")) * 2
    ok1, gas1, out1 = _PRECOMPILES[6](data, 100_000)
    ok2, gas2, out2 = _PRECOMPILES[6](data, 100_000)
    assert (ok1, gas1, out1) == (ok2, gas2, out2) and ok1
    assert precompile_cache_stats["hits"] == before["hits"] + 1
    # cached low-gas call fails exactly like the uncached path
    assert _PRECOMPILES[6](data, 10) == (False, 0, b"")
    # different input = different result, not a stale hit
    data2 = data[:-1] + bytes([data[-1] ^ 1])
    okx, _, outx = _PRECOMPILES[6](data2, 100_000)
    assert out1 != outx or not okx


# -- EIP-2537 BLS12-381 (Prague, 0x0b-0x11) ----------------------------------


def _bls():
    from reth_tpu.primitives import bls12381 as bls

    return bls


def test_bls_g1add_matches_pairing_scalar_mul():
    """Cross-validate the G1ADD field/curve arithmetic against the repo's
    independent pairing-module group law (primitives/pairing.py)."""
    from reth_tpu.evm.interpreter import _pre_bls_g1add

    bls = _bls()
    grp = g1_group(BLS12_381)
    acc = None
    for k in range(1, 12):
        acc = bls.g1_add(acc, bls.G1_GENERATOR)
        assert acc == grp.mul_scalar(BLS12_381.g1, k)
    # byte interface: G + 2G = 3G, gas charged = 375
    g = bls.encode_g1(bls.G1_GENERATOR)
    g2 = bls.encode_g1(bls.g1_add(bls.G1_GENERATOR, bls.G1_GENERATOR))
    ok, gas_left, out = _pre_bls_g1add(g + g2, GAS)
    assert ok and gas_left == GAS - 375
    assert out == bls.encode_g1(grp.mul_scalar(BLS12_381.g1, 3))
    # infinity identities + P + (-P)
    inf = b"\x00" * 128
    assert _pre_bls_g1add(inf + g, GAS)[2] == g
    neg = bls.encode_g1((bls.G1_GENERATOR[0], bls.P - bls.G1_GENERATOR[1]))
    assert _pre_bls_g1add(g + neg, GAS)[2] == inf


def test_bls_g2add_matches_pairing_scalar_mul():
    from reth_tpu.evm.interpreter import _pre_bls_g2add

    bls = _bls()
    grp = g2_group(BLS12_381)
    acc = None
    for k in range(1, 8):
        acc = bls.g2_add(acc, bls.G2_GENERATOR)
        assert acc == grp.mul_scalar(BLS12_381.g2, k)
    g = bls.encode_g2(bls.G2_GENERATOR)
    ok, gas_left, out = _pre_bls_g2add(g + g, GAS)
    assert ok and gas_left == GAS - 600
    assert out == bls.encode_g2(grp.mul_scalar(BLS12_381.g2, 2))


def test_bls_g1add_rejects_invalid_encodings():
    """EIP-2537 validation: bad length, nonzero padding, non-canonical
    field element, and off-curve points all error (consume all gas)."""
    from reth_tpu.evm.interpreter import _pre_bls_g1add

    bls = _bls()
    g = bls.encode_g1(bls.G1_GENERATOR)
    fail = (False, 0, b"")
    assert _pre_bls_g1add(g + g[:-1], GAS) == fail          # bad length
    bad_pad = bytearray(g + g)
    bad_pad[0] = 1                                          # padding byte
    assert _pre_bls_g1add(bytes(bad_pad), GAS) == fail
    too_big = b"\x00" * 16 + bls.P.to_bytes(48, "big") + g[64:] + g
    assert _pre_bls_g1add(too_big, GAS) == fail             # x >= p
    off = bytearray(g + g)
    off[127] ^= 1                                           # y tweaked
    assert _pre_bls_g1add(bytes(off), GAS) == fail
    assert _pre_bls_g1add(g + g, 374) == fail               # insufficient gas


def test_bls_pairing_check_bilinear():
    """0x0f: prod e(Pi, Qi) == 1 pinned via bilinearity — e(aG1, bG2) *
    e(-abG1, G2) == 1 while a mismatched product yields 0; infinity
    points contribute the identity; gas follows 37700 + 32600k."""
    from reth_tpu.evm.interpreter import _pre_bls_pairing

    bls = _bls()
    a, b = 5, 7
    ag = bls.g1_mul(bls.G1_GENERATOR, a)
    bq = bls.g2_mul(bls.G2_GENERATOR, b)
    abg = bls.g1_mul(bls.G1_GENERATOR, a * b)
    neg_abg = (abg[0], bls.P - abg[1])
    data = (bls.encode_g1(ag) + bls.encode_g2(bq)
            + bls.encode_g1(neg_abg) + bls.encode_g2(bls.G2_GENERATOR))
    ok, gas_left, out = _pre_bls_pairing(data, GAS)
    assert ok and out == (1).to_bytes(32, "big")
    assert GAS - gas_left == bls.pairing_gas(2)
    # non-identity product -> 0 (still a successful call)
    data_bad = bls.encode_g1(ag) + bls.encode_g2(bq)
    ok, _, out = _pre_bls_pairing(data_bad, GAS)
    assert ok and out == (0).to_bytes(32, "big")
    # infinity on either side contributes the identity
    inf_pair = b"\x00" * 128 + bls.encode_g2(bq)
    ok, _, out = _pre_bls_pairing(inf_pair, GAS)
    assert ok and out == (1).to_bytes(32, "big")


def test_bls_pairing_rejects_invalid_inputs():
    """0x0f: empty input, ragged length, out-of-subgroup points, and
    insufficient gas all fail the call (consume all gas)."""
    from reth_tpu.evm.interpreter import _pre_bls_pairing

    bls = _bls()
    fail = (False, 0, b"")
    pair = bls.encode_g1(bls.G1_GENERATOR) + bls.encode_g2(bls.G2_GENERATOR)
    assert _pre_bls_pairing(b"", GAS) == fail
    assert _pre_bls_pairing(pair[:-1], GAS) == fail
    # on-curve G1 point OUTSIDE the prime subgroup (cofactor != 1)
    x = 1
    while True:
        rhs = (x * x * x + 4) % bls.P
        y = pow(rhs, (bls.P + 1) // 4, bls.P)
        if y * y % bls.P == rhs and bls.g1_mul((x, y), bls.R) is not None:
            break
        x += 1
    bad = bls.encode_g1((x, y)) + bls.encode_g2(bls.G2_GENERATOR)
    assert _pre_bls_pairing(bad, GAS) == fail
    assert _pre_bls_pairing(pair, bls.pairing_gas(1) - 1) == fail


def test_bls_pairing_and_maps_execute_in_chain():
    """In-chain CALLs to 0x0f/0x10/0x11 now execute instead of
    invalidating the block — the PrecompileNotImplemented surface is
    closed entirely."""
    from reth_tpu.primitives.types import Account
    from reth_tpu.testing import ChainBuilder, Wallet

    bls = _bls()
    w = Wallet(0xB15)
    bld = ChainBuilder({w.address: Account(balance=10**21)})
    neg = (bls.G1_GENERATOR[0], bls.P - bls.G1_GENERATOR[1])
    pairing_input = (bls.encode_g1(bls.G1_GENERATOR)
                     + bls.encode_g2(bls.G2_GENERATOR)
                     + bls.encode_g1(neg) + bls.encode_g2(bls.G2_GENERATOR))
    bld.build_block([
        w.call(b"\x00" * 19 + b"\x0f", pairing_input, gas_limit=400_000),
        w.call(b"\x00" * 19 + b"\x10", bls._fp_encode(42), gas_limit=200_000),
        w.call(b"\x00" * 19 + b"\x11", bls._fp_encode(4) + bls._fp_encode(2),
               gas_limit=200_000),
    ])


def test_bls_iso_constants_exact_identities():
    """The baked isogeny constants satisfy the EXACT algebraic relations
    that define them — any single-coefficient typo breaks these:
    (x^3 + A'x + B') (N'D - ND')^2 == (N^3 + B_cod D^3) D  as polynomials,
    and the rescale constants obey c^3 * B_cod == b_curve, s3^2 == c^3."""
    bls = _bls()
    p = bls.P

    def check_fp():
        N, D = list(bls.ISO1_N), list(bls.ISO1_D)

        def pmul(a, b):
            r = [0] * (len(a) + len(b) - 1)
            for i, x in enumerate(a):
                for j, y in enumerate(b):
                    r[i + j] = (r[i + j] + x * y) % p
            return r

        def paddv(a, b):
            n = max(len(a), len(b))
            a = a + [0] * (n - len(a))
            b = b + [0] * (n - len(b))
            return [(x + y) % p for x, y in zip(a, b)]

        def pdiff(a):
            return [(i * c) % p for i, c in enumerate(a)][1:]

        W = paddv(pmul(pdiff(N), D),
                  [(-v) % p for v in pmul(N, pdiff(D))])
        lhs = pmul([bls.ISO1_B, bls.ISO1_A, 0, 1], pmul(W, W))
        rhs = pmul(paddv(pmul(pmul(N, N), N),
                         [bls.ISO1_BCOD * v % p
                          for v in pmul(pmul(D, D), D)]), D)
        n = max(len(lhs), len(rhs))
        assert lhs + [0] * (n - len(lhs)) == rhs + [0] * (n - len(rhs))
        assert pow(bls.ISO1_C, 3, p) * bls.ISO1_BCOD % p == 4
        assert pow(bls.ISO1_S3, 2, p) == pow(bls.ISO1_C, 3, p)

    def check_fp2():
        N, D = list(bls.ISO2_N), list(bls.ISO2_D)
        fa, fm, fs = bls._fp2_add, bls._fp2_mul, bls._fp2_sub

        def pmul(a, b):
            r = [(0, 0)] * (len(a) + len(b) - 1)
            for i, x in enumerate(a):
                for j, y in enumerate(b):
                    r[i + j] = fa(r[i + j], fm(x, y))
            return r

        def paddv(a, b):
            n = max(len(a), len(b))
            a = a + [(0, 0)] * (n - len(a))
            b = b + [(0, 0)] * (n - len(b))
            return [fa(x, y) for x, y in zip(a, b)]

        def pdiff(a):
            return [fm((i % p, 0), c) for i, c in enumerate(a)][1:]

        W = paddv(pmul(pdiff(N), D),
                  [fs((0, 0), v) for v in pmul(N, pdiff(D))])
        lhs = pmul([bls.ISO2_B, bls.ISO2_A, (0, 0), (1, 0)], pmul(W, W))
        rhs = pmul(paddv(pmul(pmul(N, N), N),
                         [fm(bls.ISO2_BCOD, v)
                          for v in pmul(pmul(D, D), D)]), D)
        n = max(len(lhs), len(rhs))
        assert lhs + [(0, 0)] * (n - len(lhs)) == rhs + [(0, 0)] * (n - len(rhs))
        c3 = bls._fp2_mul(bls._fp2_mul(bls.ISO2_C, bls.ISO2_C), bls.ISO2_C)
        assert bls._fp2_mul(c3, bls.ISO2_BCOD) == (4, 4)
        assert bls._fp2_mul(bls.ISO2_S3, bls.ISO2_S3) == c3

    check_fp()
    check_fp2()


def _expand_xmd(msg: bytes, dst: bytes, n: int) -> bytes:
    """RFC 9380 expand_message_xmd with SHA-256 (test-local reference)."""
    ell = -(-n // 32)
    dst_prime = dst + bytes([len(dst)])
    b0 = hashlib.sha256(b"\x00" * 64 + msg + n.to_bytes(2, "big")
                        + b"\x00" + dst_prime).digest()
    bv = [hashlib.sha256(b0 + b"\x01" + dst_prime).digest()]
    for i in range(2, ell + 1):
        bv.append(hashlib.sha256(
            bytes(a ^ b for a, b in zip(b0, bv[-1]))
            + bytes([i]) + dst_prime).digest())
    return b"".join(bv)[:n]


def test_bls_map_fp_to_g1_matches_rfc9380_vectors():
    """0x10 pinned END-TO-END against RFC 9380 J.9.1 hash-to-curve
    vectors: hash_to_curve(msg) == [h_eff]map(u0) + [h_eff]map(u1)
    (cofactor clearing distributes over addition), so the precompile's
    SSWU + isogeny + cofactor path must match the published points
    exactly — including the y sign conventions."""
    from reth_tpu.evm.interpreter import _pre_bls_map_fp_to_g1

    bls = _bls()
    dst = b"QUUX-V01-CS02-with-BLS12381G1_XMD:SHA-256_SSWU_RO_"
    vectors = {
        b"": (0x052926ADD2207B76CA4FA57A8734416C8DC95E24501772C814278700EED6D1E4E8CF62D9C09DB0FAC349612B759E79A1,
              0x08BA738453BFED09CB546DBB0783DBB3A5F1F566ED67BB6BE0E8C67E2E81A4CC68EE29813BB7994998F3EAE0C9C6A265),
        b"abc": (0x03567BC5EF9C690C2AB2ECDF6A96EF1C139CC0B2F284DCA0A9A7943388A49A3AEE664BA5379A7655D3C68900BE2F6903,
                 0x0B9C15F3FE6E5CF4211F346271D7B01C8F3B28BE689C8429C85B67AF215533311F0B8DFAAA154FA6B88176C229F2885D),
    }
    for msg, want in vectors.items():
        ub = _expand_xmd(msg, dst, 128)
        u = [int.from_bytes(ub[i * 64:(i + 1) * 64], "big") % bls.P
             for i in range(2)]
        pts = []
        for ui in u:
            ok, gas_left, out = _pre_bls_map_fp_to_g1(bls._fp_encode(ui), GAS)
            assert ok and GAS - gas_left == bls.MAP_FP_TO_G1_GAS
            pt = bls.decode_g1(out)
            assert bls.g1_mul(pt, bls.R) is None  # in the subgroup
            pts.append(pt)
        assert bls.g1_add(pts[0], pts[1]) == want


def test_bls_map_fp2_to_g2_matches_rfc9380_vectors():
    """0x11 pinned end-to-end against RFC 9380 J.10.1 (same
    distributivity argument as the G1 test)."""
    from reth_tpu.evm.interpreter import _pre_bls_map_fp2_to_g2

    bls = _bls()
    dst = b"QUUX-V01-CS02-with-BLS12381G2_XMD:SHA-256_SSWU_RO_"
    vectors = {
        b"": ((0x0141EBFBDCA40EB85B87142E130AB689C673CF60F1A3E98D69335266F30D9B8D4AC44C1038E9DCDD5393FAF5C41FB78A,
               0x05CB8437535E20ECFFAEF7752BADDF98034139C38452458BAEEFAB379BA13DFF5BF5DD71B72418717047F5B0F37DA03D),
              (0x0503921D7F6A12805E72940B963C0CF3471C7B2A524950CA195D11062EE75EC076DAF2D4BC358C4B190C0C98064FDD92,
               0x12424AC32561493F3FE3C260708A12B7C620E7BE00099A974E259DDC7D1F6395C3C811CDD19F1E8DBF3E9ECFDCBAB8D6)),
        b"abc": ((0x02C2D18E033B960562AAE3CAB37A27CE00D80CCD5BA4B7FE0E7A210245129DBEC7780CCC7954725F4168AFF2787776E6,
                  0x139CDDBCCDC5E91B9623EFD38C49F81A6F83F175E80B06FC374DE9EB4B41DFE4CA3A230ED250FBE3A2ACF73A41177FD8),
                 (0x1787327B68159716A37440985269CF584BCB1E621D3A7202BE6EA05C4CFE244AEB197642555A0645FB87BF7466B2BA48,
                  0x00AA65DAE3C8D732D10ECD2C50F8A1BAF3001578F71C694E03866E9F3D49AC1E1CE70DD94A733534F106D4CEC0EDDD16)),
    }
    for msg, want in vectors.items():
        ub = _expand_xmd(msg, dst, 256)
        us = []
        for i in range(2):
            e = [int.from_bytes(ub[(i * 2 + j) * 64:(i * 2 + j + 1) * 64],
                                "big") % bls.P for j in range(2)]
            us.append((e[0], e[1]))
        pts = []
        for ui in us:
            ok, gas_left, out = _pre_bls_map_fp2_to_g2(
                bls._fp_encode(ui[0]) + bls._fp_encode(ui[1]), GAS)
            assert ok and GAS - gas_left == bls.MAP_FP2_TO_G2_GAS
            pt = bls.decode_g2(out)
            assert bls.g2_mul(pt, bls.R) is None
            pts.append(pt)
        assert bls.g2_add(pts[0], pts[1]) == want


def test_bls_map_rejects_invalid_encodings():
    """0x10/0x11: wrong length, nonzero padding, non-canonical field
    element, and insufficient gas all fail the call."""
    from reth_tpu.evm.interpreter import (
        _pre_bls_map_fp_to_g1,
        _pre_bls_map_fp2_to_g2,
    )

    bls = _bls()
    fail = (False, 0, b"")
    good = bls._fp_encode(7)
    assert _pre_bls_map_fp_to_g1(good[:-1], GAS) == fail
    bad_pad = bytearray(good)
    bad_pad[0] = 1
    assert _pre_bls_map_fp_to_g1(bytes(bad_pad), GAS) == fail
    too_big = b"\x00" * 16 + bls.P.to_bytes(48, "big")
    assert _pre_bls_map_fp_to_g1(too_big, GAS) == fail
    assert _pre_bls_map_fp_to_g1(good, bls.MAP_FP_TO_G1_GAS - 1) == fail
    assert _pre_bls_map_fp2_to_g2(good, GAS) == fail  # 64 != 128 bytes
    assert _pre_bls_map_fp2_to_g2(good + too_big, GAS) == fail
    assert _pre_bls_map_fp2_to_g2(good + good,
                                  bls.MAP_FP2_TO_G2_GAS - 1) == fail


def test_bls_g1msm_matches_pairing_scalar_mul():
    """0x0c: MSM result pinned against the INDEPENDENT pairing-module
    group law; gas follows the EIP-2537 discounted per-pair formula."""
    from reth_tpu.evm.interpreter import _pre_bls_g1msm
    from reth_tpu.primitives.pairing import BLS12_381, g1_group

    bls = _bls()
    grp = g1_group(BLS12_381)
    g = bls.G1_GENERATOR
    # 3*G + 5*(2G) = 13*G
    data = (bls.encode_g1(g) + (3).to_bytes(32, "big")
            + bls.encode_g1(bls.g1_add(g, g)) + (5).to_bytes(32, "big"))
    ok, gas_left, out = _pre_bls_g1msm(data, 10**6)
    assert ok
    assert out == bls.encode_g1(grp.mul_scalar(BLS12_381.g1, 13))
    assert 10**6 - gas_left == bls.g1msm_gas(2)
    # infinity * scalar folds away; scalar 0 yields infinity
    inf = b"\x00" * 128
    assert _pre_bls_g1msm(inf + (99).to_bytes(32, "big"), 10**6)[2] == inf
    assert _pre_bls_g1msm(bls.encode_g1(g) + (0).to_bytes(32, "big"),
                          10**6)[2] == inf
    # scalars are NOT pre-reduced mod r, but r*G is still infinity
    assert _pre_bls_g1msm(bls.encode_g1(g) + bls.R.to_bytes(32, "big"),
                          10**6)[2] == inf


def test_bls_g2msm_matches_pairing_scalar_mul():
    from reth_tpu.evm.interpreter import _pre_bls_g2msm
    from reth_tpu.primitives.pairing import BLS12_381, g2_group

    bls = _bls()
    grp = g2_group(BLS12_381)
    data = bls.encode_g2(bls.G2_GENERATOR) + (7).to_bytes(32, "big")
    ok, gas_left, out = _pre_bls_g2msm(data, 10**6)
    assert ok
    assert out == bls.encode_g2(grp.mul_scalar(BLS12_381.g2, 7))
    assert 10**6 - gas_left == bls.g2msm_gas(1)


def test_bls_msm_rejects_invalid_inputs():
    """0x0c/0x0e: empty input, ragged length, off-curve points, and
    on-curve-but-out-of-subgroup points all fail the call (MSM requires
    the subgroup check ADD omits), and insufficient gas fails fast."""
    from reth_tpu.evm.interpreter import _pre_bls_g1msm

    bls = _bls()
    fail = (False, 0, b"")
    g = bls.encode_g1(bls.G1_GENERATOR)
    pair = g + (3).to_bytes(32, "big")
    assert _pre_bls_g1msm(b"", 10**6) == fail
    assert _pre_bls_g1msm(pair[:-1], 10**6) == fail
    off = bytearray(pair)
    off[127] ^= 1  # y tweaked: off-curve
    assert _pre_bls_g1msm(bytes(off), 10**6) == fail
    # find an on-curve point OUTSIDE the r-order subgroup (cofactor != 1)
    x = 1
    while True:
        rhs = (x * x * x + 4) % bls.P
        y = pow(rhs, (bls.P + 1) // 4, bls.P)
        if y * y % bls.P == rhs and bls.g1_mul((x, y), bls.R) is not None:
            break
        x += 1
    bad = bls.encode_g1((x, y)) + (1).to_bytes(32, "big")
    assert _pre_bls_g1msm(bad, 10**6) == fail
    assert _pre_bls_g1msm(pair, bls.g1msm_gas(1) - 1) == fail


def test_bls_msm_executes_in_chain():
    """An in-chain CALL to 0x0c executes normally (the whole EIP-2537
    table is implemented)."""
    from reth_tpu.primitives.types import Account
    from reth_tpu.testing import ChainBuilder, Wallet

    bls = _bls()
    a = Wallet(0xB17)
    bld = ChainBuilder({a.address: Account(balance=10**21)})
    data = bls.encode_g1(bls.G1_GENERATOR) + (3).to_bytes(32, "big")
    bld.build_block([a.call(b"\x00" * 19 + b"\x0c", data,
                            gas_limit=400_000)])
