"""Precompiles 6-10: bn254 add/mul/pairing, blake2f, KZG point evaluation.

Pairing correctness rests on property tests (bilinearity + non-degeneracy
+ mu_r membership): every non-degenerate bilinear pairing into mu_r is a
fixed power of every other, so EIP-197 product checks and KZG equality
checks are invariant across pairing choices (see primitives/pairing.py).
blake2f is pinned to the EIP-152 spec vectors.
"""

from __future__ import annotations

import hashlib

import pytest

from reth_tpu.evm.interpreter import (
    _pre_blake2f,
    _pre_bn_add,
    _pre_bn_mul,
    _pre_bn_pairing,
    _pre_point_eval,
)
from reth_tpu.primitives import kzg
from reth_tpu.primitives.pairing import (
    BLS12_381,
    BN254,
    f12_one,
    f12_pow,
    g1_group,
    g2_group,
    g2_valid,
    pairing,
    pairing_product_is_one,
)

GAS = 10**7


def _enc(*ints: int) -> bytes:
    return b"".join(i.to_bytes(32, "big") for i in ints)


# -- pairing properties ------------------------------------------------------


@pytest.mark.parametrize("curve", [BN254, BLS12_381], ids=lambda c: c.name)
def test_pairing_properties(curve):
    g1, g2 = g1_group(curve), g2_group(curve)
    assert g1.on_curve(curve.g1) and g2.on_curve(curve.g2)
    assert g1.mul_scalar(curve.g1, curve.r) is None
    assert g2.mul_scalar(curve.g2, curve.r) is None
    e = pairing(curve.g1, curve.g2, curve)
    assert e != f12_one(curve)                      # non-degenerate
    assert f12_pow(e, curve.r, curve) == f12_one(curve)  # in mu_r
    a, b = 1234567, 89101112
    eab = pairing(g1.mul_scalar(curve.g1, a), g2.mul_scalar(curve.g2, b), curve)
    assert eab == f12_pow(e, a * b, curve)          # bilinear
    neg = (curve.g1[0], (-curve.g1[1]) % curve.p)
    assert pairing_product_is_one([(curve.g1, curve.g2), (neg, curve.g2)], curve)


# -- 0x06 / 0x07: bn254 add / mul -------------------------------------------


def test_bn_add_known_double():
    # 2 * (1, 2) — the canonical EIP-196 doubling result
    ok, _, out = _pre_bn_add(_enc(1, 2, 1, 2), GAS)
    assert ok
    assert int.from_bytes(out[:32], "big") == (
        1368015179489954701390400359078579693043519447331113978918064868415326638035
    )
    assert int.from_bytes(out[32:], "big") == (
        9918110051302171585080402603319702774565515993150576347155970296011118125764
    )


def test_bn_add_identity_and_inverse():
    ok, _, out = _pre_bn_add(_enc(1, 2, 0, 0), GAS)
    assert ok and out == _enc(1, 2)
    ok, _, out = _pre_bn_add(_enc(1, 2, 1, BN254.p - 2), GAS)
    assert ok and out == _enc(0, 0)


def test_bn_mul_matches_repeated_add():
    ok, _, out = _pre_bn_mul(_enc(1, 2, 9), GAS)
    assert ok
    acc = b"\x00" * 64
    for _ in range(9):
        ok2, _, acc = _pre_bn_add(acc + _enc(1, 2), GAS)
        assert ok2
    assert out == acc


def test_bn_bad_point_rejected():
    ok, _, _ = _pre_bn_add(_enc(1, 3, 0, 0), GAS)
    assert not ok
    ok, _, _ = _pre_bn_mul(_enc(BN254.p, 2, 5), GAS)
    assert not ok


def test_bn_add_short_input_padded():
    ok, _, out = _pre_bn_add(_enc(1, 2), GAS)  # second point implied zero
    assert ok and out == _enc(1, 2)


# -- 0x08: pairing check ------------------------------------------------------


def _g2_words(q) -> bytes:
    (x0, x1), (y0, y1) = q
    return _enc(x1, x0, y1, y0)  # imaginary part first on the ABI


def test_bn_pairing_inverse_pair_is_one():
    neg = (1, BN254.p - 2)
    data = _enc(1, 2) + _g2_words(BN254.g2) + _enc(*neg) + _g2_words(BN254.g2)
    ok, _, out = _pre_bn_pairing(data, GAS)
    assert ok and int.from_bytes(out, "big") == 1


def test_bn_pairing_bilinear_cross():
    # e(2P, Q) * e(-P, 2Q)... != 1 ; e(2P, Q) * e(-2P, Q) == 1
    g1, g2 = g1_group(BN254), g2_group(BN254)
    p2 = g1.mul_scalar(BN254.g1, 2)
    np2 = (p2[0], BN254.p - p2[1])
    data = _enc(*p2) + _g2_words(BN254.g2) + _enc(*np2) + _g2_words(BN254.g2)
    ok, _, out = _pre_bn_pairing(data, GAS)
    assert ok and int.from_bytes(out, "big") == 1
    # e(2P, Q) * e(-P, Q) = e(P, Q) != 1
    neg = (1, BN254.p - 2)
    data = _enc(*p2) + _g2_words(BN254.g2) + _enc(*neg) + _g2_words(BN254.g2)
    ok, _, out = _pre_bn_pairing(data, GAS)
    assert ok and int.from_bytes(out, "big") == 0


def test_bn_pairing_empty_and_zero_points():
    ok, _, out = _pre_bn_pairing(b"", GAS)
    assert ok and int.from_bytes(out, "big") == 1
    data = _enc(0, 0) + _g2_words(BN254.g2)
    ok, _, out = _pre_bn_pairing(data, GAS)
    assert ok and int.from_bytes(out, "big") == 1


def test_bn_pairing_bad_length_or_subgroup():
    ok, _, _ = _pre_bn_pairing(b"\x00" * 191, GAS)
    assert not ok
    # a twist-curve point NOT in the r-torsion must be rejected
    g2 = g2_group(BN254)
    # find an off-subgroup point: on-curve x with y from sqrt... construct by
    # scaling the cofactor away is hard here; use an x/y that satisfies the
    # twist equation for a small multiple of a non-subgroup solution instead:
    # simplest reliable negative: corrupt one coordinate of a valid point.
    (x0, x1), (y0, y1) = BN254.g2
    bad = _enc(1, 2) + _enc(x1, x0, y1, (y0 + 1) % BN254.p)
    ok, _, _ = _pre_bn_pairing(bad, GAS)
    assert not ok


# -- 0x09: blake2f (EIP-152 spec vectors) ------------------------------------


_B2_BASE = (
    "48c9bdf267e6096a3ba7ca8485ae67bb2bf894fe72f36e3cf1361d5f3af54fa5"
    "d182e6ad7f520e511f6c3e2b8c68059b6bbd41fbabd9831f79217e1319cde05b"
    "6162630000000000000000000000000000000000000000000000000000000000"
    "0000000000000000000000000000000000000000000000000000000000000000"
    "0000000000000000000000000000000000000000000000000000000000000000"
    "0000000000000000000000000000000000000000000000000000000000000000"
    "0300000000000000" "0000000000000000" "01"
)


def test_blake2f_eip152_vector_12_rounds():
    data = bytes.fromhex("0000000c" + _B2_BASE)
    ok, gas_left, out = _pre_blake2f(data, GAS)
    assert ok and gas_left == GAS - 12
    assert out.hex() == (
        "ba80a53f981c4d0d6a2797b69f12f6e94c212f14685ac4b74b12bb6fdbffa2d1"
        "7d87c5392aab792dc252d5de4533cc9518d38aa8dbf1925ab92386edd4009923"
    )
    # and it must equal stdlib blake2b for the same message
    assert out == hashlib.blake2b(b"abc", digest_size=64).digest()


def test_blake2f_zero_rounds_and_bad_input():
    data = bytes.fromhex("00000000" + _B2_BASE)
    ok, gas_left, out = _pre_blake2f(data, GAS)
    assert ok and gas_left == GAS and len(out) == 64
    ok, _, _ = _pre_blake2f(data[:-1], GAS)          # 212 bytes
    assert not ok
    ok, _, _ = _pre_blake2f(data[:-1] + b"\x02", GAS)  # bad final flag
    assert not ok


# -- 0x0a: KZG point evaluation ----------------------------------------------


def _point_eval_input(coeffs, z, y=None, proof=None, vh=None):
    true_y, true_proof = kzg.prove_monomial(coeffs, z)
    commitment = kzg.commit_monomial(coeffs)
    cb = kzg.g1_to_bytes(commitment)
    pb = kzg.g1_to_bytes(proof if proof is not None else true_proof)
    return (
        (vh if vh is not None else kzg.kzg_to_versioned_hash(cb))
        + z.to_bytes(32, "big")
        + (y if y is not None else true_y).to_bytes(32, "big")
        + cb
        + pb
    )


def test_point_eval_valid_proof():
    coeffs = [7, 11, 13, 17]  # p(X) = 7 + 11X + 13X^2 + 17X^3
    data = _point_eval_input(coeffs, z=12345)
    ok, gas_left, out = _pre_point_eval(data, GAS)
    assert ok, "valid KZG proof rejected"
    assert gas_left == GAS - 50000
    assert int.from_bytes(out[:32], "big") == kzg.FIELD_ELEMENTS_PER_BLOB
    assert int.from_bytes(out[32:], "big") == kzg.BLS_MODULUS


def test_point_eval_wrong_y_rejected():
    coeffs = [7, 11, 13, 17]
    true_y, _ = kzg.prove_monomial(coeffs, 12345)
    data = _point_eval_input(coeffs, z=12345, y=(true_y + 1) % kzg.BLS_MODULUS)
    ok, _, _ = _pre_point_eval(data, GAS)
    assert not ok


def test_point_eval_wrong_versioned_hash_rejected():
    data = _point_eval_input([3, 5], z=9, vh=b"\x01" + b"\x00" * 31)
    ok, _, _ = _pre_point_eval(data, GAS)
    assert not ok


def test_point_eval_bad_length_rejected():
    ok, _, _ = _pre_point_eval(b"\x00" * 191, GAS)
    assert not ok


def test_g1_serialization_roundtrip():
    g1 = g1_group(BLS12_381)
    for k in (1, 2, 3, 7777):
        pt = g1.mul_scalar(BLS12_381.g1, k)
        assert kzg.g1_from_bytes(kzg.g1_to_bytes(pt)) == pt
    assert kzg.g1_from_bytes(kzg.g1_to_bytes(None)) is None


def test_g2_serialization_parses_generator_compressed():
    from reth_tpu.primitives.kzg import g2_from_bytes

    # compress the generator by hand: c1 || c0 with flag bits on c1
    (x0, x1), (y0, y1) = BLS12_381.g2
    is_largest = (y1 > (BLS12_381.p - 1) // 2) or (
        y1 == 0 and y0 > (BLS12_381.p - 1) // 2
    )
    raw = x1.to_bytes(48, "big") + x0.to_bytes(48, "big")
    flags = 0x80 | (0x20 if is_largest else 0)
    data = bytes([raw[0] | flags]) + raw[1:]
    assert g2_from_bytes(data) == BLS12_381.g2


def test_precompile_cache_hits_and_correctness():
    """Repeated identical precompile calls serve from the cache with the
    same output and gas (reference precompile_cache.rs); low-gas calls
    fail identically whether cached or not."""
    from reth_tpu.evm.interpreter import (
        _PRECOMPILE_CACHE,
        _PRECOMPILES,
        precompile_cache_stats,
    )

    _PRECOMPILE_CACHE.clear()
    before = dict(precompile_cache_stats)
    # bn254 add of two generator points, twice
    from reth_tpu.primitives.pairing import BN254

    gx, gy = BN254.g1
    data = (gx.to_bytes(32, "big") + gy.to_bytes(32, "big")) * 2
    ok1, gas1, out1 = _PRECOMPILES[6](data, 100_000)
    ok2, gas2, out2 = _PRECOMPILES[6](data, 100_000)
    assert (ok1, gas1, out1) == (ok2, gas2, out2) and ok1
    assert precompile_cache_stats["hits"] == before["hits"] + 1
    # cached low-gas call fails exactly like the uncached path
    assert _PRECOMPILES[6](data, 10) == (False, 0, b"")
    # different input = different result, not a stale hit
    data2 = data[:-1] + bytes([data[-1] ^ 1])
    okx, _, outx = _PRECOMPILES[6](data2, 100_000)
    assert out1 != outx or not okx


# -- EIP-2537 BLS12-381 (Prague, 0x0b-0x11) ----------------------------------


def _bls():
    from reth_tpu.primitives import bls12381 as bls

    return bls


def test_bls_g1add_matches_pairing_scalar_mul():
    """Cross-validate the G1ADD field/curve arithmetic against the repo's
    independent pairing-module group law (primitives/pairing.py)."""
    from reth_tpu.evm.interpreter import _pre_bls_g1add

    bls = _bls()
    grp = g1_group(BLS12_381)
    acc = None
    for k in range(1, 12):
        acc = bls.g1_add(acc, bls.G1_GENERATOR)
        assert acc == grp.mul_scalar(BLS12_381.g1, k)
    # byte interface: G + 2G = 3G, gas charged = 375
    g = bls.encode_g1(bls.G1_GENERATOR)
    g2 = bls.encode_g1(bls.g1_add(bls.G1_GENERATOR, bls.G1_GENERATOR))
    ok, gas_left, out = _pre_bls_g1add(g + g2, GAS)
    assert ok and gas_left == GAS - 375
    assert out == bls.encode_g1(grp.mul_scalar(BLS12_381.g1, 3))
    # infinity identities + P + (-P)
    inf = b"\x00" * 128
    assert _pre_bls_g1add(inf + g, GAS)[2] == g
    neg = bls.encode_g1((bls.G1_GENERATOR[0], bls.P - bls.G1_GENERATOR[1]))
    assert _pre_bls_g1add(g + neg, GAS)[2] == inf


def test_bls_g2add_matches_pairing_scalar_mul():
    from reth_tpu.evm.interpreter import _pre_bls_g2add

    bls = _bls()
    grp = g2_group(BLS12_381)
    acc = None
    for k in range(1, 8):
        acc = bls.g2_add(acc, bls.G2_GENERATOR)
        assert acc == grp.mul_scalar(BLS12_381.g2, k)
    g = bls.encode_g2(bls.G2_GENERATOR)
    ok, gas_left, out = _pre_bls_g2add(g + g, GAS)
    assert ok and gas_left == GAS - 600
    assert out == bls.encode_g2(grp.mul_scalar(BLS12_381.g2, 2))


def test_bls_g1add_rejects_invalid_encodings():
    """EIP-2537 validation: bad length, nonzero padding, non-canonical
    field element, and off-curve points all error (consume all gas)."""
    from reth_tpu.evm.interpreter import _pre_bls_g1add

    bls = _bls()
    g = bls.encode_g1(bls.G1_GENERATOR)
    fail = (False, 0, b"")
    assert _pre_bls_g1add(g + g[:-1], GAS) == fail          # bad length
    bad_pad = bytearray(g + g)
    bad_pad[0] = 1                                          # padding byte
    assert _pre_bls_g1add(bytes(bad_pad), GAS) == fail
    too_big = b"\x00" * 16 + bls.P.to_bytes(48, "big") + g[64:] + g
    assert _pre_bls_g1add(too_big, GAS) == fail             # x >= p
    off = bytearray(g + g)
    off[127] ^= 1                                           # y tweaked
    assert _pre_bls_g1add(bytes(off), GAS) == fail
    assert _pre_bls_g1add(g + g, 374) == fail               # insufficient gas


def test_bls_unimplemented_ops_fail_block_loudly():
    """Calls to 0x0f-0x11 (pairing check, map-to-curve) must raise a
    BlockExecutionError-backed failure, never act as an empty account
    (round-5 verdict: a silent stub breaks the native/interpreter
    bit-identical invariant unnoticed)."""
    import pytest as _pytest

    from reth_tpu.evm.executor import BlockExecutionError
    from reth_tpu.evm.interpreter import (
        PrecompileNotImplemented,
        _precompile,
    )
    from reth_tpu.primitives.types import Account
    from reth_tpu.testing import ChainBuilder, Wallet

    pairing_addr = b"\x00" * 19 + b"\x0f"
    fn = _precompile(pairing_addr)
    assert fn is not None, "0x0f must be in the Prague precompile table"
    with _pytest.raises(PrecompileNotImplemented):
        fn(b"", 10**6)
    # in-chain: a tx calling the pairing precompile invalidates the block
    a = Wallet(0xB15)
    bld = ChainBuilder({a.address: Account(balance=10**21)})
    with _pytest.raises(BlockExecutionError, match="0x0f"):
        bld.build_block([a.call(pairing_addr, b"", gas_limit=400_000)])
    # ...while the implemented ADDs execute normally in-chain
    bls = _bls()
    g = bls.encode_g1(bls.G1_GENERATOR)
    b = Wallet(0xB16)
    bld2 = ChainBuilder({b.address: Account(balance=10**21)})
    bld2.build_block([b.call(b"\x00" * 19 + b"\x0b", g + g,
                             gas_limit=400_000)])


def test_bls_g1msm_matches_pairing_scalar_mul():
    """0x0c: MSM result pinned against the INDEPENDENT pairing-module
    group law; gas follows the EIP-2537 discounted per-pair formula."""
    from reth_tpu.evm.interpreter import _pre_bls_g1msm
    from reth_tpu.primitives.pairing import BLS12_381, g1_group

    bls = _bls()
    grp = g1_group(BLS12_381)
    g = bls.G1_GENERATOR
    # 3*G + 5*(2G) = 13*G
    data = (bls.encode_g1(g) + (3).to_bytes(32, "big")
            + bls.encode_g1(bls.g1_add(g, g)) + (5).to_bytes(32, "big"))
    ok, gas_left, out = _pre_bls_g1msm(data, 10**6)
    assert ok
    assert out == bls.encode_g1(grp.mul_scalar(BLS12_381.g1, 13))
    assert 10**6 - gas_left == bls.g1msm_gas(2)
    # infinity * scalar folds away; scalar 0 yields infinity
    inf = b"\x00" * 128
    assert _pre_bls_g1msm(inf + (99).to_bytes(32, "big"), 10**6)[2] == inf
    assert _pre_bls_g1msm(bls.encode_g1(g) + (0).to_bytes(32, "big"),
                          10**6)[2] == inf
    # scalars are NOT pre-reduced mod r, but r*G is still infinity
    assert _pre_bls_g1msm(bls.encode_g1(g) + bls.R.to_bytes(32, "big"),
                          10**6)[2] == inf


def test_bls_g2msm_matches_pairing_scalar_mul():
    from reth_tpu.evm.interpreter import _pre_bls_g2msm
    from reth_tpu.primitives.pairing import BLS12_381, g2_group

    bls = _bls()
    grp = g2_group(BLS12_381)
    data = bls.encode_g2(bls.G2_GENERATOR) + (7).to_bytes(32, "big")
    ok, gas_left, out = _pre_bls_g2msm(data, 10**6)
    assert ok
    assert out == bls.encode_g2(grp.mul_scalar(BLS12_381.g2, 7))
    assert 10**6 - gas_left == bls.g2msm_gas(1)


def test_bls_msm_rejects_invalid_inputs():
    """0x0c/0x0e: empty input, ragged length, off-curve points, and
    on-curve-but-out-of-subgroup points all fail the call (MSM requires
    the subgroup check ADD omits), and insufficient gas fails fast."""
    from reth_tpu.evm.interpreter import _pre_bls_g1msm

    bls = _bls()
    fail = (False, 0, b"")
    g = bls.encode_g1(bls.G1_GENERATOR)
    pair = g + (3).to_bytes(32, "big")
    assert _pre_bls_g1msm(b"", 10**6) == fail
    assert _pre_bls_g1msm(pair[:-1], 10**6) == fail
    off = bytearray(pair)
    off[127] ^= 1  # y tweaked: off-curve
    assert _pre_bls_g1msm(bytes(off), 10**6) == fail
    # find an on-curve point OUTSIDE the r-order subgroup (cofactor != 1)
    x = 1
    while True:
        rhs = (x * x * x + 4) % bls.P
        y = pow(rhs, (bls.P + 1) // 4, bls.P)
        if y * y % bls.P == rhs and bls.g1_mul((x, y), bls.R) is not None:
            break
        x += 1
    bad = bls.encode_g1((x, y)) + (1).to_bytes(32, "big")
    assert _pre_bls_g1msm(bad, 10**6) == fail
    assert _pre_bls_g1msm(pair, bls.g1msm_gas(1) - 1) == fail


def test_bls_msm_executes_in_chain():
    """An in-chain CALL to 0x0c now executes instead of invalidating the
    block (the PrecompileNotImplemented surface shrank to 0x0f-0x11)."""
    from reth_tpu.primitives.types import Account
    from reth_tpu.testing import ChainBuilder, Wallet

    bls = _bls()
    a = Wallet(0xB17)
    bld = ChainBuilder({a.address: Account(balance=10**21)})
    data = bls.encode_g1(bls.G1_GENERATOR) + (3).to_bytes(32, "big")
    bld.build_block([a.call(b"\x00" * 19 + b"\x0c", data,
                            gas_limit=400_000)])
