"""Paged COW B+tree engine (native/pagedkv.cpp): durability, crash
recovery, structural scale, and space reuse.

Reference analogue: the properties MDBX gives the reference client —
shadow-paged commits with O(1) recovery (no WAL replay), mmap reads,
DUPSORT sub-databases, page recycling through a persisted free list
(crates/storage/libmdbx-rs/mdbx-sys/libmdbx).
"""

from __future__ import annotations

import hashlib
import os
import signal
import subprocess
import sys
import textwrap

import pytest


def paged_db(path):
    from reth_tpu.storage.native import PagedDb

    try:
        return PagedDb(path)
    except Exception as e:
        pytest.skip(f"paged backend unavailable: {e}")


def sha(i: int) -> bytes:
    return hashlib.sha256(str(i).encode()).digest()


def test_reopen_multi_commit(tmp_path):
    d = tmp_path / "kv"
    db = paged_db(d)
    for batch in range(5):
        with db.tx_mut() as tx:
            for i in range(200):
                tx.put("t", sha(batch * 200 + i), b"v%d" % (batch * 200 + i))
    db.close()
    db2 = paged_db(d)
    with db2.tx() as tx:
        assert tx.entry_count("t") == 1000
        assert tx.get("t", sha(777)) == b"v777"
        keys = [k for k, _ in tx.cursor("t").walk()]
        assert keys == sorted(keys) and len(keys) == 1000
    db2.close()


def test_dup_subtree_spill_and_unspill(tmp_path):
    """Large duplicate sets spill to a nested B+tree; semantics unchanged."""
    db = paged_db(tmp_path / "kv")
    vals = sorted(os.urandom(40) for _ in range(500))
    with db.tx_mut() as tx:
        for v in reversed(vals):
            tx.put("d", b"hot-key", v, dupsort=True)
        tx.put("d", b"cold", b"single", dupsort=True)
    with db.tx() as tx:
        assert tx.entry_count("d") == 501
        assert tx.get_dups("d", b"hot-key") == vals
        # ranged dup seek inside the subtree
        cur = tx.cursor("d")
        mid = vals[250]
        assert cur.seek_by_key_subkey(b"hot-key", mid) == (b"hot-key", mid)
        assert cur.next_dup() == (b"hot-key", vals[251])
        # cross-key iteration: hot-key dups then cold
        assert cur.seek(b"hot-key") == (b"hot-key", vals[0])
    with db.tx_mut() as tx:
        for v in vals[:499]:
            assert tx.delete("d", b"hot-key", v)
    with db.tx() as tx:
        assert tx.get_dups("d", b"hot-key") == [vals[499]]
        assert tx.entry_count("d") == 2
    db.close()


def test_overflow_values_roundtrip_and_replace(tmp_path):
    db = paged_db(tmp_path / "kv")
    big1 = os.urandom(30_000)
    big2 = os.urandom(70_000)
    with db.tx_mut() as tx:
        tx.put("t", b"blob", big1)
    with db.tx_mut() as tx:
        tx.put("t", b"blob", big2)  # replaces: frees the old chain
    with db.tx() as tx:
        assert tx.get("t", b"blob") == big2
    db.close()
    db2 = paged_db(tmp_path / "kv")
    assert db2.tx().get("t", b"blob") == big2
    db2.close()


def test_space_reuse_under_churn(tmp_path):
    """Freed pages recycle through the free list: steady-state overwrite
    churn must not grow the file unboundedly (the MDBX property that the
    std::map WAL engine cannot offer)."""
    d = tmp_path / "kv"
    db = paged_db(d)
    with db.tx_mut() as tx:
        for i in range(2000):
            tx.put("t", sha(i), os.urandom(64))
    size_after_load = (d / "data.rtpg").stat().st_size
    for _round in range(30):
        with db.tx_mut() as tx:
            for i in range(0, 2000, 10):
                tx.put("t", sha(i), os.urandom(64))
    size_after_churn = (d / "data.rtpg").stat().st_size
    db.close()
    # generous bound: churn rewrites the same keys; space must be recycled
    assert size_after_churn < size_after_load * 3, (
        f"file grew {size_after_load} -> {size_after_churn}: free list broken"
    )


def test_crash_recovery_kill9(tmp_path):
    """SIGKILL mid-commit-stream: reopen recovers a consistent recent state
    (dual-meta flip — no WAL replay, no partial commits visible)."""
    d = tmp_path / "kv"
    script = textwrap.dedent(
        """
        import sys
        sys.path.insert(0, %r)
        from reth_tpu.storage.native import PagedDb
        db = PagedDb(%r)
        i = 0
        while True:
            with db.tx_mut() as tx:
                # each commit writes a consistent (count, payload) pair
                tx.put("t", b"count", str(i).encode())
                tx.put("t", b"k%%06d" %% i, b"x" * 100)
            i += 1
            print(i, flush=True)
        """
    ) % (str(os.getcwd()), str(d))
    proc = subprocess.Popen(
        [sys.executable, "-c", script],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
    )
    # wait until it has committed a few hundred batches, then SIGKILL
    seen = 0
    for line in proc.stdout:
        seen = int(line)
        if seen >= 300:
            os.kill(proc.pid, signal.SIGKILL)
            break
    proc.wait(timeout=30)
    assert seen >= 300
    db = paged_db(d)
    with db.tx() as tx:
        count = int(tx.get("t", b"count"))
        # recovered state is one of the committed states (possibly the last)
        assert count >= seen - 2
        # and it is internally consistent: every k up to count exists
        for i in (0, count // 2, count):
            assert tx.get("t", b"k%06d" % i) == b"x" * 100, i
    db.close()


def test_clear_and_recreate_table(tmp_path):
    db = paged_db(tmp_path / "kv")
    with db.tx_mut() as tx:
        for i in range(500):
            tx.put("t", sha(i), b"v")
        tx.put("d", b"k", b"a", dupsort=True)
        tx.put("d", b"k", b"b", dupsort=True)
    with db.tx_mut() as tx:
        tx.clear("t")
        tx.clear("d")
    with db.tx() as tx:
        assert tx.entry_count("t") == 0
        assert tx.cursor("t").first() is None
        assert tx.get_dups("d", b"k") == []
    with db.tx_mut() as tx:
        tx.put("t", b"fresh", b"start")
    assert db.tx().get("t", b"fresh") == b"start"
    db.close()


def test_write_txn_sees_own_writes_via_cursor(tmp_path):
    """Live-view cursor semantics: a write txn's own mutations are visible
    to cursors created before the mutation (MemDb contract)."""
    db = paged_db(tmp_path / "kv")
    with db.tx_mut() as tx:
        tx.put("t", b"a", b"1")
        tx.put("t", b"c", b"3")
    tx = db.tx_mut()
    cur = tx.cursor("t")
    assert cur.first() == (b"a", b"1")
    tx.put("t", b"b", b"2")
    assert cur.next() == (b"b", b"2")
    tx.delete("t", b"c")
    assert cur.next() is None
    tx.abort()
    db.close()


def test_pipeline_e2e_on_paged_backend(tmp_path):
    """The full staged sync runs unchanged over the paged engine."""
    from reth_tpu.consensus import EthBeaconConsensus
    from reth_tpu.primitives import Account
    from reth_tpu.primitives.keccak import keccak256_batch_np
    from reth_tpu.stages import Pipeline, default_stages
    from reth_tpu.storage import ProviderFactory
    from reth_tpu.storage.genesis import import_chain, init_genesis
    from reth_tpu.testing import ChainBuilder, Wallet
    from reth_tpu.trie import TrieCommitter

    CPU = TrieCommitter(hasher=keccak256_batch_np)
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)}, committer=CPU)
    for i in range(3):
        builder.build_block([alice.transfer(b"\x0b" * 20, 100 + i)])

    factory = ProviderFactory(paged_db(tmp_path / "node"))
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis, committer=CPU)
    import_chain(factory, builder.blocks[1:], EthBeaconConsensus(CPU))
    Pipeline(factory, default_stages(committer=CPU)).run(3)
    p = factory.provider()
    assert p.stage_checkpoint("Finish") == 3
    assert p.header_by_number(3).state_root == builder.blocks[3].header.state_root
    assert p.account(b"\x0b" * 20).balance == 303
