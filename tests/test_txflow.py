"""Production write path: firehose -> continuous block production.

Covers the PR-18 surfaces end to end:

- ``BlockProducer`` differential correctness: at pool-sequence parity the
  standing hot candidate must be **bit-identical** to a from-scratch serial
  greedy build over a clone of the pool (same selection, same order), under
  randomized submission mixes, nonce-gap promotion, blob-fee gating, and
  same-slot replacement races.
- ``TxBatcher`` bounded backpressure: synchronous shedding with
  ``PoolOverloaded`` carrying ``retry_after_s``, surfaced over RPC as
  ``-32005`` with structured ``error.data``.
- ``ReplicaPoolView``: the ``pt_*`` feed record family (snapshot anchor,
  incremental add/replace/drop/canon, gap detection -> resubscribe).
- Pool event plane: monotonic ``seq`` and the add/replace/drop/canon kinds
  the feed publisher relies on.
- Node wiring for ``continuous_build`` plus the chaos ``pool`` domain and
  the ``txflow`` bench mode (slow drills).
"""

import json
import os
import random
import subprocess
import sys
import time
from pathlib import Path

import pytest

from reth_tpu.engine import EngineTree
from reth_tpu.engine.local import LocalMiner
from reth_tpu.payload import build_payload
from reth_tpu.payload.producer import BlockProducer
from reth_tpu.pool import PoolError, PoolOverloaded, TransactionPool, TxBatcher
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.primitives.types import Transaction
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.storage.genesis import init_genesis
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)

SINK = b"\x0f" * 20


def make_env(n_wallets=3, cancun=False):
    wallets = [Wallet(0x7F000 + i) for i in range(n_wallets)]
    builder = ChainBuilder(
        {w.address: Account(balance=10**21) for w in wallets},
        committer=CPU, cancun=cancun,
    )
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis, committer=CPU)
    tree = EngineTree(factory, committer=CPU, persistence_threshold=2)
    pool = TransactionPool(lambda: tree.overlay_provider())
    pool.base_fee = 10**9
    return tree, pool, wallets


@pytest.fixture
def producer_env():
    tree, pool, wallets = make_env()
    prod = BlockProducer(tree, pool, interval=0.01)
    prod.start()
    try:
        yield tree, pool, wallets, prod
    finally:
        prod.stop()


def wait_parity(prod, pool, tree, timeout=10.0):
    """Wait until the hot candidate has caught up with every pool event,
    then return (selected_hashes, parent_hash, attrs) as one atomic read."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        with prod._lock:
            cand = prod.candidate
            if (cand is not None and cand.window is None
                    and cand.parent_hash == tree.head_hash
                    and cand.pool_seq == pool.event_seq):
                return ([t.hash for t in cand.selected], cand.parent_hash,
                        cand.attrs)
        time.sleep(0.005)
    raise AssertionError(
        f"producer never reached pool parity: {prod.snapshot()}")


def clone_pool(pool):
    """Fresh pool with identical contents, replayed in submission order so
    the selection heap's tie-breaks (submission_id) match the original."""
    clone = TransactionPool(pool.state_reader, config=pool.config)
    clone.base_fee = pool.base_fee
    clone.blob_base_fee = pool.blob_base_fee
    with pool._lock:
        pooled = sorted(pool.by_hash.values(), key=lambda p: p.submission_id)
        for p in pooled:
            if p.tx.tx_type == 3:
                clone.add_blob_transaction(p.tx, pool.get_blob_sidecar(p.tx.hash))
            else:
                clone.add_transaction(p.tx, sender=p.sender)
    return clone


def serial_selection(tree, pool, parent, attrs):
    """From-scratch greedy build over a pool clone — the reference the
    incremental producer must match bit-for-bit."""
    block, _fees = build_payload(tree, clone_pool(pool), parent, attrs)
    return [t.hash for t in block.transactions]


# -- producer differential correctness ---------------------------------------


def test_producer_matches_serial_greedy_randomized(producer_env):
    tree, pool, wallets, prod = producer_env
    rng = random.Random(0x7AF10)
    miner = LocalMiner(tree, pool, producer=prod)
    for rnd in range(4):
        for _ in range(rng.randint(4, 10)):
            w = rng.choice(wallets)
            tip = rng.choice([10**9, 2 * 10**9, 5 * 10**9])
            tx = w.transfer(SINK, rng.randint(1, 10**6),
                            max_priority_fee_per_gas=tip)
            pool.add_transaction(tx)
            roll = rng.random()
            if roll < 0.25:
                repl = w.sign_tx(Transaction(
                    tx_type=2, chain_id=1, nonce=tx.nonce,
                    max_fee_per_gas=tx.max_fee_per_gas * 2,
                    max_priority_fee_per_gas=tip * 2,
                    gas_limit=21_000, to=SINK, value=7), bump_nonce=False)
                pool.add_transaction(repl)
            elif roll < 0.40:
                with pytest.raises(PoolError, match="already known"):
                    pool.add_transaction(tx)
        got, parent, attrs = wait_parity(prod, pool, tree)
        want = serial_selection(tree, pool, parent, attrs)
        assert got == want, f"round {rnd}: producer diverged from serial greedy"
        blk = miner.mine_block()
        assert [t.hash for t in blk.transactions] == got
    assert miner.producer_seals == 4 and miner.serial_builds == 0
    snap = prod.snapshot()
    assert snap["sealed"] == 4 and snap["errors"] == 0
    assert prod.hits >= 1


def test_producer_nonce_gap_promotion_is_incremental(producer_env):
    tree, pool, wallets, prod = producer_env
    w = wallets[0]
    t0 = w.transfer(SINK, 1)                       # nonce 0
    w.nonce = 2
    t2 = w.transfer(SINK, 3)                       # nonce 2 (gapped)
    w.nonce = 1
    t1 = w.transfer(SINK, 2)                       # the gap filler
    pool.add_transaction(t0)
    pool.add_transaction(t2)
    got, _, _ = wait_parity(prod, pool, tree)
    assert got == [t0.hash]                        # t2 queued behind the gap
    rebuilds = prod.full_rebuilds
    ranks = prod.exec_ranks
    pool.add_transaction(t1)                       # promotes t1 AND t2
    got, parent, attrs = wait_parity(prod, pool, tree)
    assert got == [t0.hash, t1.hash, t2.hash]
    # the promotion extends the candidate from the considered-trace suffix:
    # new execution happened, but never a from-scratch rebuild
    assert prod.full_rebuilds == rebuilds
    assert prod.exec_ranks >= ranks + 2
    assert got == serial_selection(tree, pool, parent, attrs)


def test_producer_replacement_race_and_single_slot_mined(producer_env):
    tree, pool, wallets, prod = producer_env
    w = wallets[0]
    base = w.transfer(SINK, 10)
    pool.add_transaction(base)
    got, _, _ = wait_parity(prod, pool, tree)
    assert got == [base.hash]
    repl = w.sign_tx(Transaction(
        tx_type=2, chain_id=1, nonce=base.nonce,
        max_fee_per_gas=base.max_fee_per_gas * 2,
        max_priority_fee_per_gas=base.max_priority_fee_per_gas * 2,
        gas_limit=21_000, to=SINK, value=11), bump_nonce=False)
    pool.add_transaction(repl)
    # +5% on the *original* fees is far below the 10% bump over the live
    # occupant (already at 2x) -> rejected, candidate untouched
    under = w.sign_tx(Transaction(
        tx_type=2, chain_id=1, nonce=base.nonce,
        max_fee_per_gas=base.max_fee_per_gas * 105 // 100,
        max_priority_fee_per_gas=base.max_priority_fee_per_gas * 105 // 100,
        gas_limit=21_000, to=SINK, value=12), bump_nonce=False)
    with pytest.raises(PoolError, match="underpriced"):
        pool.add_transaction(under)
    got, parent, attrs = wait_parity(prod, pool, tree)
    assert got == [repl.hash]                      # slot raced, winner only
    assert got == serial_selection(tree, pool, parent, attrs)
    blk = LocalMiner(tree, pool, producer=prod).mine_block()
    assert [t.hash for t in blk.transactions] == [repl.hash]
    # the slot is spent: even a 10x late replacement is nonce-too-low now
    late = w.sign_tx(Transaction(
        tx_type=2, chain_id=1, nonce=base.nonce,
        max_fee_per_gas=base.max_fee_per_gas * 10,
        max_priority_fee_per_gas=base.max_priority_fee_per_gas * 10,
        gas_limit=21_000, to=SINK, value=13), bump_nonce=False)
    with pytest.raises(PoolError, match="nonce too low"):
        pool.add_transaction(late)


def test_producer_blob_fee_gating():
    from tests.test_blob_pool import make_sidecar

    tree, pool, wallets = make_env(cancun=True)
    w = wallets[0]
    sidecar = make_sidecar(n_blobs=1, seed=7)
    blob_tx = w.sign_tx(Transaction(
        tx_type=3, chain_id=1, nonce=0, max_fee_per_gas=10**10,
        max_priority_fee_per_gas=10**9, gas_limit=21_000, to=SINK,
        max_fee_per_blob_gas=5,
        blob_versioned_hashes=sidecar.versioned_hashes()))
    plain = wallets[1].transfer(SINK, 1)
    prod = BlockProducer(tree, pool, interval=0.01)
    prod.start()
    try:
        pool.add_blob_transaction(blob_tx, sidecar)
        pool.add_transaction(plain)
        # blob market spikes above the tx's cap: the candidate must shed
        # the blob tx while keeping the plain one
        pool.on_canonical_state_change(10**9, blob_base_fee=50)
        got, _, _ = wait_parity(prod, pool, tree)
        assert got == [plain.hash]
        # market cools below the cap: blob tx flows back in, and the hot
        # candidate still matches a from-scratch build over a pool clone
        pool.on_canonical_state_change(10**9, blob_base_fee=3)
        got, parent, attrs = wait_parity(prod, pool, tree)
        assert blob_tx.hash in got and plain.hash in got
        assert got == serial_selection(tree, pool, parent, attrs)
    finally:
        prod.stop()


# -- firehose backpressure ---------------------------------------------------


def test_batcher_sheds_with_retry_after_when_saturated():
    tree, pool, wallets = make_env(1)
    w = wallets[0]
    batcher = TxBatcher(pool, max_batch=1, max_queue=4, retry_after_s=0.25)
    try:
        futs = []
        shed = None
        with pool._lock:                 # wedge the insert worker mid-batch
            for i in range(64):
                f = batcher.submit(w.transfer(SINK, i + 1))
                futs.append(f)
                if f.done():             # only sheds fail synchronously
                    shed = f
                    break
                time.sleep(0.005)
            assert shed is not None, "queue never saturated"
            err = shed.exception()
            assert isinstance(err, PoolOverloaded)
            assert isinstance(err, PoolError)
            assert err.retry_after_s == 0.25
            assert batcher.sheds >= 1
        # lock released: the queued (non-shed) futures must all resolve
        for f in futs[:-1]:
            assert isinstance(f.result(timeout=10), bytes)
        assert batcher.processed == len(futs) - 1
        assert batcher.batches >= 1
    finally:
        batcher.close()


def test_rpc_send_raw_transaction_sheds_as_32005():
    from reth_tpu.rpc.eth import EthApi
    from reth_tpu.rpc.server import RpcError

    tree, pool, wallets = make_env(1)
    w = wallets[0]
    batcher = TxBatcher(pool, max_batch=1, max_queue=1, retry_after_s=0.7)
    api = EthApi(tree, pool=pool, tx_batcher=batcher)
    try:
        with pool._lock:                 # wedge the worker; saturate the queue
            saturated = False
            for i in range(64):
                f = batcher.submit(w.transfer(SINK, i + 1))
                if f.done():
                    saturated = True
                    break
                time.sleep(0.005)
            assert saturated
            raw = "0x" + w.transfer(SINK, 999).encode().hex()
            with pytest.raises(RpcError) as ei:
                api.eth_sendRawTransaction(raw)
        assert ei.value.code == -32005
        assert ei.value.data["class"] == "tx"
        assert ei.value.data["retry_after"] == 0.7
    finally:
        batcher.close()


# -- pool event plane + pt_* replica view ------------------------------------


def test_pool_event_plane_kinds_and_sequencing():
    tree, pool, wallets = make_env(1)
    w = wallets[0]
    events = []
    pool.add_listener(events.append)
    t0 = w.transfer(SINK, 1)
    pool.add_transaction(t0)
    w.nonce = 0
    repl = w.transfer(SINK, 2, max_fee_per_gas=200 * 10**9,
                      max_priority_fee_per_gas=2 * 10**9)
    pool.add_transaction(repl)
    t1 = w.transfer(SINK, 3)                       # nonce 1
    pool.add_transaction(t1)
    pool.remove_invalid(t1.hash)
    pool.on_canonical_state_change(2 * 10**9)
    assert [e["kind"] for e in events] == [
        "add", "replace", "add", "drop", "canon"]
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    assert events[1]["old_hash"] == t0.hash
    assert events[1]["tx"].hash == repl.hash
    assert events[3]["reason"] == "invalid"
    assert events[4]["base_fee"] == 2 * 10**9
    pool.remove_listener(events.append)


def test_replica_pool_view_pt_record_family():
    from reth_tpu.fleet.replica import ReplicaPoolView

    w = Wallet(0xB10B)
    t0 = w.transfer(SINK, 1)
    t1 = w.transfer(SINK, 2)
    w.nonce = 1
    t1b = w.transfer(SINK, 3, max_fee_per_gas=200 * 10**9,
                     max_priority_fee_per_gas=2 * 10**9)
    view = ReplicaPoolView()
    # incremental records are ignored until a snapshot anchors the view
    assert view.apply({"type": "pt_add", "seq": 1, "tx": t0.encode(),
                       "sender": w.address}) == "ok"
    assert view.seq == -1 and not view.txs
    assert view.apply({"type": "pt_snapshot", "seq": 4, "base_fee": 10**9,
                       "blob_base_fee": 1,
                       "txs": [(t0.encode(), w.address)]}) == "ok"
    assert view.seq == 4 and t0.hash in view.txs
    # records at or below the snapshot seq are already folded in
    assert view.apply({"type": "pt_add", "seq": 4, "tx": t1.encode(),
                       "sender": w.address}) == "ok"
    assert t1.hash not in view.txs
    assert view.apply({"type": "pt_add", "seq": 5, "tx": t1.encode(),
                       "sender": w.address}) == "ok"
    assert view.by_sender[w.address][1] == t1.hash
    # replacement evicts the old hash and takes the (sender, nonce) slot
    assert view.apply({"type": "pt_replace", "seq": 6, "tx": t1b.encode(),
                       "old_hash": t1.hash, "sender": w.address}) == "ok"
    assert t1.hash not in view.txs
    assert view.by_sender[w.address][1] == t1b.hash
    assert view.apply({"type": "pt_canon", "seq": 7, "base_fee": 2 * 10**9,
                       "blob_base_fee": 3}) == "ok"
    assert view.base_fee == 2 * 10**9 and view.blob_base_fee == 3
    assert view.apply({"type": "pt_drop", "seq": 8, "hash": t1b.hash}) == "ok"
    assert t1b.hash not in view.txs
    # a seq gap means lost records: reset to unsynced and ask to resubscribe
    assert view.apply({"type": "pt_drop", "seq": 10, "hash": t0.hash}) == "gap"
    assert view.seq == -1
    assert view.records >= 4 and view.snapshots == 1


# -- node wiring + chaos matrix ----------------------------------------------


def test_node_continuous_build_wiring():
    from reth_tpu.node import Node, NodeConfig

    w = Wallet(0xA11CE)
    builder = ChainBuilder({w.address: Account(balance=10**21)}, committer=CPU)
    cfg = NodeConfig(dev=True, genesis_header=builder.genesis,
                     genesis_alloc=builder.accounts_at_genesis,
                     continuous_build=True, http_port=0, authrpc_port=0)
    node = Node(cfg, committer=CPU)
    try:
        node.start_rpc()
        assert node.producer is not None
        assert node.miner.producer is node.producer
        assert node.payload_service.producer is node.producer
        # firehose -> hot candidate -> sealed through the producer
        node.tx_batcher.add_sync(w.transfer(SINK, 123))
        blk = node.miner.mine_block()
        assert len(blk.transactions) == 1
        assert node.miner.producer_seals == 1
        assert node.miner.serial_builds == 0
        snap = node.producer.snapshot()
        assert snap["sealed"] == 1 and snap["errors"] == 0
        # the ranks gauge re-anchors to 0 once the mined txs leave the
        # pool, even though the rebuild-to-empty is not a stream-changing
        # refresh
        from reth_tpu.metrics import producer_metrics
        deadline = time.monotonic() + 10
        while (time.monotonic() < deadline
               and producer_metrics.last.get("ranks") != 0):
            time.sleep(0.01)
        assert producer_metrics.last.get("ranks") == 0
        # producer_status rides the normal RPC dispatch
        resp = json.loads(node.rpc.handle(json.dumps(
            {"jsonrpc": "2.0", "id": 1, "method": "producer_status",
             "params": []}).encode()))
        assert resp["result"]["sealed"] == 1
    finally:
        node.stop()


def test_pool_scenario_deterministic_and_isolated():
    from reth_tpu.chaos import (
        make_fleet_scenario,
        make_ha_scenario,
        make_pool_scenario,
        make_scenario,
    )

    for seed in (1, 5, 9):
        a, b = make_pool_scenario(seed), make_pool_scenario(seed)
        assert a == b
        assert a["domain"] == "pool" and a["mode"] == "kill"
        assert 4 <= a["kill_after"] <= 7
    # own rng stream: drawing other domains' scenarios must not perturb it
    before = make_pool_scenario(3)
    make_scenario(3), make_fleet_scenario(3), make_ha_scenario(3)
    assert make_pool_scenario(3) == before
    # the seed actually varies the matrix
    assert any(make_pool_scenario(s) != make_pool_scenario(1)
               for s in range(2, 6))


@pytest.mark.slow
def test_pool_chaos_single_seed(tmp_path):
    from reth_tpu.chaos import make_pool_scenario, run_pool_scenario

    scn = make_pool_scenario(1)
    res = run_pool_scenario(scn, tmp_path, timeout=420)
    assert res.get("ok") is True, res
    inv = res.get("invariants", {})
    for k in ("head_consistent", "loss_bound", "no_stuck_candidate",
              "liveness", "replacement_semantics", "replacement_mined",
              "replica_pending_view", "no_leaked_lease"):
        assert inv.get(k) is True, (k, res)


@pytest.mark.slow
def test_pool_chaos_campaign_ten_seeds(tmp_path):
    from reth_tpu.chaos import run_campaign

    results = run_campaign(range(1, 11), tmp_path, domain="pool")
    assert len(results) == 10
    bad = [r for r in results if not r.get("ok")]
    assert not bad, bad


@pytest.mark.slow
def test_bench_txflow_mode_end_to_end():
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("RETH_TPU_FAULT_")}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(JAX_PLATFORMS="cpu", RETH_TPU_BENCH_MODE="txflow",
               RETH_TPU_BENCH_TXFLOW_RATES="800",
               RETH_TPU_BENCH_TXFLOW_WALLETS="6",
               RETH_TPU_BENCH_TXFLOW_TXS="4")
    repo = Path(__file__).resolve().parent.parent
    r = subprocess.run([sys.executable, str(repo / "bench.py")],
                       capture_output=True, text=True, timeout=560,
                       env=env, cwd=repo)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    line = json.loads(r.stdout.strip().splitlines()[-1])
    assert line["metric"] == "txflow_inclusion_p99_ms"
    assert line.get("error") is None, line
    assert line["value"] > 0
