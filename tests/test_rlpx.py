"""ECIES + RLPx transport: crypto roundtrips, handshake secrets, frames,
snappy codec, Hello exchange over real sockets.
"""

from __future__ import annotations

import os
import socket
import threading

import pytest

from reth_tpu.net import snappy
from reth_tpu.net.ecies import (
    EciesError,
    Handshake,
    decrypt,
    derive_secrets,
    encrypt,
)
from reth_tpu.net.rlpx import RlpxError, RlpxSession, initiate, node_id, respond
from reth_tpu.primitives.keccak import Keccak256, keccak256
from reth_tpu.primitives.secp256k1 import pubkey_from_priv

A_PRIV = 0x1111111111111111111111111111111111111111111111111111111111111111
B_PRIV = 0x2222222222222222222222222222222222222222222222222222222222222222


# -- streaming keccak --------------------------------------------------------


def test_streaming_keccak_matches_oneshot():
    data = bytes(range(256)) * 3
    k = Keccak256()
    for i in range(0, len(data), 37):  # uneven chunks across block borders
        k.update(data[i : i + 37])
    assert k.digest() == keccak256(data)
    # digest() must not disturb the running state
    k2 = Keccak256(data)
    _ = k2.digest()
    k2.update(b"more")
    assert k2.digest() == keccak256(data + b"more")


# -- snappy ------------------------------------------------------------------


@pytest.mark.parametrize("payload", [
    b"", b"a", b"hello world", bytes(range(256)),
    b"ab" * 5000,                      # highly compressible
    # incompressible but DETERMINISTIC (xdist workers must collect
    # identical parametrize ids)
    b"".join(__import__("hashlib").sha256(bytes([i])).digest()
             for i in range(94)),
    b"\x00" * 100000,
])
def test_snappy_roundtrip(payload):
    c = snappy.compress(payload)
    assert snappy.decompress(c) == payload


def test_snappy_compresses_repetitive_data():
    data = b"reth-tpu " * 1000
    assert len(snappy.compress(data)) < len(data) // 4


def test_snappy_decode_known_vector():
    # literal-only stream: len=5, tag (5-1)<<2, bytes
    assert snappy.decompress(bytes([5, 4 << 2]) + b"abcde") == b"abcde"
    # copy: "aaaa..." via 1-byte literal + copy1 (len 7, offset 1)
    enc = bytes([8, 0]) + b"a" + bytes([1 | (3 << 2) | (0 << 5), 1])
    assert snappy.decompress(enc) == b"a" * 8


def test_snappy_rejects_malformed():
    with pytest.raises(snappy.SnappyError):
        snappy.decompress(bytes([10, 4 << 2]) + b"abcde")  # length mismatch
    with pytest.raises(snappy.SnappyError):
        snappy.decompress(bytes([4, 2 | (3 << 2), 9, 0]))  # offset > output


# -- ECIES -------------------------------------------------------------------


def test_ecies_roundtrip_and_tamper():
    pytest.importorskip("cryptography")  # AES for RLPx/discv5 paths
    pub = pubkey_from_priv(B_PRIV)
    msg = b"secret handshake payload"
    ct = encrypt(pub, msg, shared_mac_data=b"\x01\x02")
    assert decrypt(B_PRIV, ct, shared_mac_data=b"\x01\x02") == msg
    with pytest.raises(EciesError):
        decrypt(B_PRIV, ct, shared_mac_data=b"\x01\x03")  # wrong mac data
    bad = bytearray(ct)
    bad[100] ^= 1
    with pytest.raises(EciesError):
        decrypt(B_PRIV, bytes(bad))
    with pytest.raises(EciesError):
        decrypt(A_PRIV, ct)  # wrong recipient


def test_handshake_both_sides_derive_same_keys():
    pytest.importorskip("cryptography")  # AES for RLPx/discv5 paths
    init = Handshake(A_PRIV)
    resp = Handshake(B_PRIV)
    auth = init.auth(pubkey_from_priv(B_PRIV))
    ack, s_resp = resp.on_auth(auth)
    s_init = init.finalize_initiator(ack)
    assert s_init.aes == s_resp.aes
    assert s_init.mac == s_resp.mac
    # MAC states are cross-seeded: my egress == peer's ingress
    assert s_init.egress_mac.digest() == s_resp.ingress_mac.digest()
    assert s_init.ingress_mac.digest() == s_resp.egress_mac.digest()
    assert resp.remote_pub == pubkey_from_priv(A_PRIV)


def test_handshake_rejects_wrong_recipient():
    pytest.importorskip("cryptography")  # AES for RLPx/discv5 paths
    init = Handshake(A_PRIV)
    auth = init.auth(pubkey_from_priv(B_PRIV))
    eve = Handshake(0x3333)
    with pytest.raises(EciesError):
        eve.on_auth(auth)


# -- RLPx frames over sockets ------------------------------------------------


def _session_pair():
    a, b = socket.socketpair()
    out = {}

    def server():
        out["resp"] = respond(b, B_PRIV)

    t = threading.Thread(target=server)
    t.start()
    out["init"] = initiate(a, A_PRIV, pubkey_from_priv(B_PRIV))
    t.join(timeout=30)
    return out["init"], out["resp"]


def test_rlpx_frames_bidirectional():
    pytest.importorskip("cryptography")  # AES for RLPx/discv5 paths
    s1, s2 = _session_pair()
    s1.send_frame(b"\x80hello over rlpx")
    assert s2.recv_frame() == b"\x80hello over rlpx"
    s2.send_frame(b"\x80reply")
    assert s1.recv_frame() == b"\x80reply"
    # many frames keep the rolling MACs in sync
    for i in range(20):
        payload = os.urandom(1 + i * 37)
        s1.send_frame(payload)
        assert s2.recv_frame() == payload
    s1.close()
    s2.close()


def test_rlpx_tampered_frame_rejected():
    pytest.importorskip("cryptography")  # AES for RLPx/discv5 paths
    s1, s2 = _session_pair()
    raw_sock = s1.sock
    s1.send_frame(b"\x80data")
    # flip one ciphertext bit in flight
    buf = s2.sock.recv(65536, socket.MSG_PEEK)
    assert buf
    data = bytearray(s2.sock.recv(65536))
    data[20] ^= 1
    r, w = socket.socketpair()
    w.sendall(bytes(data))
    s2.sock = r
    with pytest.raises(RlpxError):
        s2.recv_frame()
    raw_sock.close()


def test_rlpx_hello_and_snappy_messages():
    pytest.importorskip("cryptography")  # AES for RLPx/discv5 paths
    s1, s2 = _session_pair()
    result = {}

    def peer():
        result["hello"] = s2.hello(B_PRIV, "reth-tpu/test-b", [("eth", 68)])

    t = threading.Thread(target=peer)
    t.start()
    remote = s1.hello(A_PRIV, "reth-tpu/test-a", [("eth", 68)], port=30303)
    t.join(timeout=30)
    assert remote["client_id"] == "reth-tpu/test-b"
    assert remote["caps"] == [("eth", 68)]
    assert result["hello"]["port"] == 30303
    assert result["hello"]["node_id"] == node_id(A_PRIV)
    assert s1.snappy_enabled and s2.snappy_enabled
    # capability messages now travel snappy-compressed
    body = b"\xaa" * 10_000
    s1.send_msg(0x10, body)
    msg_id, got = s2.recv_msg()
    assert (msg_id, got) == (0x10, body)
    s1.close()
    s2.close()
