"""Bit-exactness of the JAX keccak kernel vs the CPU reference.

Runs on the virtual CPU mesh in tests; the same program runs unchanged on
TPU (uint32 ops only, static shapes).
"""

import numpy as np
import pytest

from reth_tpu.primitives.keccak import keccak256, RATE
from reth_tpu.ops import keccak256_batch_jax, KeccakDevice, keccak_f1600_jax


def test_f1600_zero_state():
    import jax.numpy as jnp

    lo, hi = keccak_f1600_jax(jnp.zeros((25, 1), jnp.uint32), jnp.zeros((25, 1), jnp.uint32))
    lane0 = int(lo[0, 0]) | (int(hi[0, 0]) << 32)
    assert lane0 == 0xF1258F7940E1DDE7


@pytest.mark.parametrize("ln", [0, 1, 31, 32, 55, 107, RATE - 1, RATE, 2 * RATE - 1, 531, 1000])
def test_matches_reference_lengths(ln):
    rng = np.random.default_rng(ln)
    msgs = [bytes(rng.integers(0, 256, size=ln, dtype=np.uint8)) for _ in range(5)]
    got = keccak256_batch_jax(msgs)
    assert got == [keccak256(m) for m in msgs]


def test_mixed_batch_order_and_tiers():
    rng = np.random.default_rng(7)
    # 100 messages of mixed lengths: crosses tier padding and several buckets
    msgs = [bytes(rng.integers(0, 256, size=int(l), dtype=np.uint8))
            for l in rng.integers(0, 400, size=100)]
    dev = KeccakDevice(min_tier=8)
    got = dev.hash_batch(msgs)
    assert got == [keccak256(m) for m in msgs]


def test_single_and_empty():
    dev = KeccakDevice()
    assert dev.hash_one(b"") == keccak256(b"")
    assert dev.hash_batch([]) == []


def test_masked_large_messages():
    """Messages > MAX_EXACT_BLOCKS blocks route through the masked tier kernel."""
    rng = np.random.default_rng(11)
    # 1223 B -> 9 blocks, 2040 B -> 16 blocks (exact tier edge), 2176 B = 16*136
    msgs = [bytes(rng.integers(0, 256, size=n, dtype=np.uint8))
            for n in (1223, 2040, 2175, 2176, 24576)]  # incl. max contract code size
    dev = KeccakDevice()
    assert dev.hash_batch(msgs) == [keccak256(m) for m in msgs]


def test_masked_tier_merges_mixed_counts():
    """9..16-block messages share ONE tier-16 launch with real per-msg counts."""
    rng = np.random.default_rng(13)
    msgs = [bytes(rng.integers(0, 256, size=n, dtype=np.uint8))
            for n in range(1100, 2170, 137)]  # block counts 9..16 mixed
    dev = KeccakDevice()
    launches = []
    orig = dev._hash_bucket
    dev_hash = lambda sub, key, counts: (launches.append((key, len(sub))), orig(sub, key, counts))[1]
    dev._hash_bucket = dev_hash
    got = dev.hash_batch(msgs)
    assert got == [keccak256(m) for m in msgs]
    assert len(launches) == 1 and launches[0][0] == 16 and launches[0][1] == len(msgs)


def test_known_vector_through_device():
    assert keccak256_batch_jax([b"abc"])[0].hex() == (
        "4e03657aea45a94fc7d47ba826c8d667c0d1e6e33a64a036ec44f58fa12d6c45"
    )
