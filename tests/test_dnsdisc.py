"""DNS discovery (EIP-1459 ENR trees) over a dict-backed resolver.

Reference analogue: crates/net/dns tree-walk + root verification tests
(src/tree.rs); no real DNS is involved — the resolver seam is the point.
"""

import pytest

from reth_tpu.net.dnsdisc import (
    DnsDiscError,
    DnsResolver,
    EnrTree,
    link_url,
    parse_link,
)
from reth_tpu.net.enr import make_enr
from reth_tpu.primitives.secp256k1 import pubkey_from_priv, random_priv

TREE_KEY = 0x58D23B55BC9CDCE1F18C2500F40FF4AB411BF7437BEDBC55AF4E6289B29244AA


def _make_enrs(n, base_port=30000):
    return [make_enr(random_priv(), ip="127.0.0.1", udp=base_port + i,
                     tcp=base_port + i) for i in range(n)]


def test_tree_build_and_resolve():
    enrs = _make_enrs(30)  # forces multi-level branch records
    records = EnrTree(TREE_KEY, seq=3).build("nodes.example.org", enrs)
    resolver = DnsResolver(records.get)
    got = resolver.resolve_tree(
        link_url(pubkey_from_priv(TREE_KEY), "nodes.example.org"))
    assert {e.node_id for e in got} == {e.node_id for e in enrs}


def test_root_signature_verified():
    enrs = _make_enrs(2)
    records = EnrTree(TREE_KEY).build("nodes.example.org", enrs)
    wrong_key = pubkey_from_priv(0xBEEF)
    resolver = DnsResolver(records.get)
    with pytest.raises(DnsDiscError):
        resolver.resolve_tree(link_url(wrong_key, "nodes.example.org"))


def test_poisoned_record_skipped():
    enrs = _make_enrs(3)
    records = EnrTree(TREE_KEY).build("nodes.example.org", enrs)
    # corrupt one leaf: content no longer matches its subdomain hash
    leaf_fqdn = next(k for k, v in records.items()
                     if v.startswith("enr:") and "." in k)
    records[leaf_fqdn] = enrs[0].to_base64() + "x"
    resolver = DnsResolver(records.get)
    got = resolver.resolve_tree(
        link_url(pubkey_from_priv(TREE_KEY), "nodes.example.org"))
    assert len(got) == 2  # the poisoned leaf is dropped, others survive


def test_linked_trees_followed():
    enrs_a, enrs_b = _make_enrs(2), _make_enrs(2, 31000)
    key_b = random_priv()
    rec_b = EnrTree(key_b).build("b.example.org", enrs_b)
    rec_a = EnrTree(TREE_KEY).build(
        "a.example.org", enrs_a,
        links=[link_url(pubkey_from_priv(key_b), "b.example.org")])
    table = {**rec_a, **rec_b}
    got = DnsResolver(table.get).resolve_tree(
        link_url(pubkey_from_priv(TREE_KEY), "a.example.org"))
    assert {e.node_id for e in got} == {e.node_id for e in enrs_a + enrs_b}


def test_link_roundtrip():
    pub = pubkey_from_priv(TREE_KEY)
    url = link_url(pub, "nodes.example.org")
    back_pub, domain = parse_link(url)
    assert back_pub == pub and domain == "nodes.example.org"
