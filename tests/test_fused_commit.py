"""Fused multi-level device commit: parity with the per-level committer.

The fused path (reth_tpu/ops/fused_commit.py) keeps child digests resident
on-device and splices them into host-built RLP templates; these tests pin
its roots, branch-node collection, and proof spines to the round-1
per-level committer (itself pinned to the naive oracle + known vectors in
test_trie.py). Runs on the virtual CPU mesh (conftest).
"""

from __future__ import annotations

import numpy as np
import pytest

from reth_tpu.primitives.keccak import keccak256, keccak256_batch_np
from reth_tpu.primitives.nibbles import unpack_nibbles
from reth_tpu.primitives.rlp import rlp_encode
from reth_tpu.trie.committer import TrieCommitter


def _random_leaves(n: int, seed: int, val_len=(1, 100)):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, 256, size=(n, 32), dtype=np.uint8)
    out = []
    seen = set()
    for i in range(n):
        k = keys[i].tobytes()
        if k in seen:
            continue
        seen.add(k)
        vlen = int(rng.integers(*val_len))
        out.append((unpack_nibbles(k), rlp_encode(bytes(rng.integers(0, 256, size=vlen, dtype=np.uint8)))))
    return out


@pytest.fixture(scope="module")
def fused():
    return TrieCommitter(fused=True, min_tier=8)


@pytest.fixture(scope="module")
def baseline():
    return TrieCommitter(hasher=keccak256_batch_np)


@pytest.mark.parametrize("n", [1, 2, 17, 100, 700])
def test_fused_root_parity(fused, baseline, n):
    leaves = _random_leaves(n, seed=n)
    assert fused.commit(leaves).root == baseline.commit(leaves).root


def test_fused_single_tiny_leaf(fused):
    # root RLP < 32 bytes: root hash is still keccak(rlp), resolved host-side
    leaves = [(unpack_nibbles(b"\x11" * 32), rlp_encode(b"\x01"))]
    r = fused.commit(leaves)
    assert r.root == TrieCommitter(hasher=keccak256_batch_np).commit(leaves).root
    assert len(r.root) == 32


def test_fused_branch_nodes_match(fused, baseline):
    leaves = _random_leaves(300, seed=7)
    a = fused.commit(leaves, collect_branches=True)
    b = baseline.commit(leaves, collect_branches=True)
    assert a.root == b.root
    assert a.branch_nodes == b.branch_nodes
    assert a.hashed_nodes == b.hashed_nodes


def test_fused_commit_many_storage_and_accounts(fused, baseline):
    jobs = [(_random_leaves(50, seed=100 + i, val_len=(1, 32)), None) for i in range(6)]
    jobs.append((_random_leaves(400, seed=200), None))
    ra = fused.commit_many(jobs, collect_branches=False)
    rb = baseline.commit_many(jobs, collect_branches=False)
    assert [r.root for r in ra] == [r.root for r in rb]


def test_fused_boundaries(fused, baseline):
    """Opaque unchanged-subtree refs splice as literal bytes (no holes)."""
    leaves = _random_leaves(200, seed=3)
    full = baseline.commit(leaves, collect_branches=True)
    # carve out one deep branch subtree as an opaque boundary
    path = max((p for p in full.branch_nodes if len(p) > 0), key=len)
    kept = [(p, v) for p, v in leaves if p[: len(path)] != path]
    assert len(kept) < len(leaves), "expected leaves under the carved branch"
    got = fused.commit(kept, boundaries={path: _subtree_hash(baseline, leaves, path)})
    assert got.root == full.root


def _subtree_hash(committer, leaves, path):
    """Hash of the node at ``path`` inside the full trie: leaf/ext paths are
    relative, so committing the sub-leaves with ``path`` stripped rebuilds
    the identical subtree node."""
    sub = [(p[len(path) :], v) for p, v in leaves if p[: len(path)] == path]
    return committer.commit(sub).root


def test_fused_mesh_parity(baseline):
    """The SPMD-sharded fused engine (FusedMeshEngine) on the virtual
    8-device CPU mesh produces identical roots/branch nodes — including with
    a min_tier not divisible by the device count (rounded up internally)."""
    import jax
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    sharded = TrieCommitter(fused=True, min_tier=12, mesh=mesh)
    leaves = _random_leaves(500, seed=42)
    a = sharded.commit(leaves, collect_branches=True)
    b = baseline.commit(leaves, collect_branches=True)
    assert a.root == b.root
    assert a.branch_nodes == b.branch_nodes


def test_fused_proof_spines(fused, baseline):
    leaves = _random_leaves(150, seed=9)
    target = leaves[17][0]
    a = fused.commit_many([(leaves, None)], proof_targets=[[target]])[0]
    b = baseline.commit_many([(leaves, None)], proof_targets=[[target]])[0]
    assert a.root == b.root
    assert a.proof_nodes == b.proof_nodes
    # spine must start at the root and the root node must hash to the root
    root_rlp = a.proof_nodes[b""]
    assert keccak256(root_rlp) == a.root


def test_fused_empty_and_single_jobs(fused):
    from reth_tpu.primitives.types import EMPTY_ROOT_HASH

    rs = fused.commit_many([([], None), (_random_leaves(3, seed=1), None)])
    assert rs[0].root == EMPTY_ROOT_HASH
    assert len(rs[1].root) == 32


def test_fused_deep_nesting_shared_prefixes(fused, baseline):
    """Long shared prefixes exercise extension nodes + multi-level splicing."""
    leaves = []
    for i in range(64):
        k = bytes([0xAB] * 16) + i.to_bytes(16, "big")
        leaves.append((unpack_nibbles(k), rlp_encode(bytes([i + 1]))))
    assert fused.commit(leaves).root == baseline.commit(leaves).root
