"""Trie tests: known vectors, naive-vs-committer equality, state roots."""

import numpy as np
import pytest

from reth_tpu.primitives import Account, EMPTY_ROOT_HASH, keccak256
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.primitives.nibbles import unpack_nibbles
from reth_tpu.primitives.rlp import rlp_encode, encode_int
from reth_tpu.trie import (
    TrieCommitter,
    naive_trie_root,
    naive_secure_root,
    state_root,
    storage_root,
)

CPU = keccak256_batch_np  # deterministic CPU hasher for structure tests


def committer():
    return TrieCommitter(hasher=CPU)


# --- known vectors from ethereum/tests trietest.json ------------------------

def test_empty_trie():
    assert naive_trie_root({}) == EMPTY_ROOT_HASH
    assert committer().commit([]).root == EMPTY_ROOT_HASH


def test_known_vector_branching():
    pairs = {
        b"do": b"verb",
        b"dog": b"puppy",
        b"doge": b"coin",
        b"horse": b"stallion",
    }
    expect = "5991bb8c6514148a29db676a14ac506cd2cd5775ace63c30a4fe457715e9ac84"
    assert naive_trie_root(pairs).hex() == expect


def test_known_vector_single():
    assert naive_trie_root({b"A": b"aaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaa"}).hex() == (
        "d23786fb4a010da3ce639d66d5e904a11dbc02746d1ce25029e53290cabf28ab"
    )


def test_known_vector_hex_encoded_secure():
    # from hex_encoded_securetrie_test.json: three accounts
    pairs = {
        bytes.fromhex("0000000000000000000000000000000000000000000000000000000000000045"):
            bytes.fromhex("22b224a1420a802ab51d326e29fa98e34c4f24ea"),
        bytes.fromhex("0000000000000000000000000000000000000000000000000000000000000046"):
            bytes.fromhex("67706c2076330000000000000000000000000000000000000000000000000000"),
    }
    # cross-check naive vs committer only (no published root memorised);
    # naive_secure_root does NOT rlp-wrap values — build equivalently
    got_naive = naive_secure_root(pairs)
    leaves = [(unpack_nibbles(keccak256(k)), v) for k, v in pairs.items()]
    got_committer = committer().commit(leaves).root
    assert got_naive == got_committer


# --- naive vs committer equality on random tries ----------------------------

@pytest.mark.parametrize("n,seed", [(1, 0), (2, 1), (5, 2), (17, 3), (100, 4), (500, 5)])
def test_committer_matches_naive_random(n, seed):
    rng = np.random.default_rng(seed)
    pairs = {}
    for _ in range(n):
        klen = int(rng.integers(1, 8))
        key = bytes(rng.integers(0, 256, size=klen, dtype=np.uint8))
        val = bytes(rng.integers(0, 256, size=int(rng.integers(1, 40)), dtype=np.uint8))
        pairs[key] = val
    want = naive_trie_root(pairs)
    leaves = [(unpack_nibbles(k), v) for k, v in pairs.items()]
    got = committer().commit(leaves)
    assert got.root == want


def test_committer_matches_naive_secure_32byte_keys():
    rng = np.random.default_rng(9)
    pairs = {
        bytes(rng.integers(0, 256, size=32, dtype=np.uint8)): rlp_encode(
            bytes(rng.integers(0, 256, size=30, dtype=np.uint8))
        )
        for _ in range(300)
    }
    hashed = {keccak256(k): v for k, v in pairs.items()}
    # naive takes raw value; committer takes rlp-encoded leaf value: feed same
    want = naive_trie_root(hashed)
    got = committer().commit([(unpack_nibbles(k), v) for k, v in hashed.items()]).root
    assert got == want


def test_branch_value_keys_prefix_of_each_other():
    pairs = {b"\x01\x23": b"aa", b"\x01\x23\x45": b"bb", b"\x01": b"cc"}
    want = naive_trie_root(pairs)
    got = committer().commit([(unpack_nibbles(k), v) for k, v in pairs.items()])
    assert got.root == want


# --- boundaries (incremental skeleton) --------------------------------------

def test_opaque_boundary_reproduces_full_root():
    """Replacing an unchanged subtree by its hash must not change the root."""
    rng = np.random.default_rng(12)
    pairs = {
        bytes(rng.integers(0, 256, size=32, dtype=np.uint8)): rlp_encode(b"v" + bytes([i]))
        for i in range(64)
    }
    leaves = sorted((unpack_nibbles(k), v) for k, v in pairs.items())
    full = committer().commit(leaves)
    # pick a stored branch at depth 1, replace its whole subtree by its hash
    deep_branches = [p for p in full.branch_nodes if len(p) == 1]
    assert deep_branches, "expected branches at depth 1"
    cut = deep_branches[0]
    # compute subtree hash: the branch node's ref from the parent (root) node
    root_branch = full.branch_nodes[b""]
    child_hash = root_branch.child_hash(cut[0])
    assert child_hash is not None
    kept = [(p, v) for p, v in leaves if p[: len(cut)] != cut]
    got = committer().commit(kept, boundaries={cut: child_hash})
    assert got.root == full.root


def test_committer_with_device_hasher():
    """Full state root through the JAX kernel (virtual CPU mesh in tests)."""
    from reth_tpu.ops import KeccakDevice

    rng = np.random.default_rng(21)
    accounts = {
        bytes(rng.integers(0, 256, size=20, dtype=np.uint8)): Account(
            nonce=int(rng.integers(0, 100)), balance=int(rng.integers(1, 10**18))
        )
        for _ in range(50)
    }
    storages = {
        addr: {
            bytes(rng.integers(0, 256, size=32, dtype=np.uint8)): int(rng.integers(1, 2**62))
            for _ in range(5)
        }
        for addr in list(accounts)[:10]
    }
    dev = TrieCommitter(hasher=KeccakDevice().hash_batch)
    cpu = TrieCommitter(hasher=CPU)
    got_dev, _ = state_root(accounts, storages, committer=dev)
    got_cpu, _ = state_root(accounts, storages, committer=cpu)
    assert got_dev == got_cpu


# --- state roots -------------------------------------------------------------

def test_state_root_accounts_only():
    accounts = {
        bytes.fromhex("a94f5374fce5edbc8e2a8697c15331677e6ebf0b"): Account(
            nonce=0, balance=0x0DE0B6B3A7640000
        ),
        bytes.fromhex("095e7baea6a6c7c4c2dfeb977efac326af552d87"): Account(
            nonce=1, balance=0x0DE0B6B3A76586A0
        ),
    }
    want = naive_secure_root({a: acc.trie_encode() for a, acc in accounts.items()})
    got, details = state_root(accounts, committer=committer())
    assert got == want
    assert set(details["storage_roots"]) == set()


def test_state_root_with_storage():
    addr1 = b"\x11" * 20
    addr2 = b"\x22" * 20
    accounts = {addr1: Account(balance=1), addr2: Account(nonce=2, balance=5)}
    storages = {addr1: {b"\x00" * 32: 7, b"\x01".rjust(32, b"\x00"): 0, b"\x02".rjust(32, b"\x00"): 99}}
    # oracle: per-account storage roots via naive secure trie
    sr1 = naive_secure_root({
        b"\x00" * 32: rlp_encode(encode_int(7)),
        b"\x02".rjust(32, b"\x00"): rlp_encode(encode_int(99)),
    })
    want = naive_secure_root({
        addr1: accounts[addr1].with_(storage_root=sr1).trie_encode(),
        addr2: accounts[addr2].trie_encode(),
    })
    got, details = state_root(accounts, storages, committer=committer())
    assert details["storage_roots"][addr1] == sr1
    assert got == want


def test_storage_root_standalone():
    slots = {b"\x00" * 32: 1234, b"\x05".rjust(32, b"\x00"): 0}
    want = naive_secure_root({b"\x00" * 32: rlp_encode(encode_int(1234))})
    assert storage_root(slots, committer=committer()) == want
    assert storage_root({}, committer=committer()) == EMPTY_ROOT_HASH


def test_empty_account_excluded():
    addr = b"\x01" * 20
    got, _ = state_root({addr: Account()}, committer=committer())
    assert got == EMPTY_ROOT_HASH


def test_cleared_storage_recomputes_empty_root():
    """An account whose last slot was zeroed must land on EMPTY_ROOT_HASH."""
    addr = b"\x42" * 20
    stale = b"\xde" * 32
    accounts = {addr: Account(balance=1, storage_root=stale)}
    got, details = state_root(accounts, {addr: {b"\x00" * 32: 0}}, committer=committer())
    assert details["storage_roots"][addr] == EMPTY_ROOT_HASH
    want = naive_secure_root({addr: Account(balance=1).trie_encode()})
    assert got == want


def test_opaque_root_boundary_returns_hash():
    h = b"\x9a" * 32
    assert committer().commit([], boundaries={b"": h}).root == h
