"""WebSocket RPC transport + admin_ namespace."""

from __future__ import annotations

import base64
import hashlib
import json
import os
import socket
import struct

import pytest

from reth_tpu.rpc.server import RpcServer
from reth_tpu.rpc.ws import OP_PING, OP_TEXT, WsRpcServer, _WS_GUID


def _ws_client(port):
    sock = socket.create_connection(("127.0.0.1", port), timeout=10)
    key = base64.b64encode(os.urandom(16))
    sock.sendall(
        b"GET / HTTP/1.1\r\nHost: x\r\nUpgrade: websocket\r\n"
        b"Connection: Upgrade\r\nSec-WebSocket-Key: " + key +
        b"\r\nSec-WebSocket-Version: 13\r\n\r\n"
    )
    resp = b""
    while b"\r\n\r\n" not in resp:
        resp += sock.recv(4096)
    assert b"101" in resp.split(b"\r\n")[0]
    want = base64.b64encode(hashlib.sha1(key + _WS_GUID).digest())
    assert want in resp
    return sock


def _send_text(sock, payload: bytes, opcode=OP_TEXT):
    mask = os.urandom(4)
    header = bytes([0x80 | opcode])
    n = len(payload)
    if n < 126:
        header += bytes([0x80 | n])
    else:
        header += bytes([0x80 | 126]) + struct.pack(">H", n)
    body = bytes(c ^ mask[i % 4] for i, c in enumerate(payload))
    sock.sendall(header + mask + body)


def _recv_msg(sock):
    b0, b1 = sock.recv(1)[0], sock.recv(1)[0]
    ln = b1 & 0x7F
    if ln == 126:
        (ln,) = struct.unpack(">H", sock.recv(2))
    elif ln == 127:
        (ln,) = struct.unpack(">Q", sock.recv(8))
    buf = b""
    while len(buf) < ln:
        buf += sock.recv(ln - len(buf))
    return b0 & 0x0F, buf


def test_ws_rpc_roundtrip():
    rpc = RpcServer()
    rpc.register_method("test_echo", lambda x: x * 2)
    ws = WsRpcServer(rpc)
    port = ws.start()
    try:
        sock = _ws_client(port)
        _send_text(sock, json.dumps({"jsonrpc": "2.0", "id": 7,
                                     "method": "test_echo", "params": [21]}).encode())
        op, body = _recv_msg(sock)
        assert op == OP_TEXT
        assert json.loads(body) == {"jsonrpc": "2.0", "id": 7, "result": 42}
        # ping -> pong
        _send_text(sock, b"hi", opcode=OP_PING)
        op, body = _recv_msg(sock)
        assert op == 10 and body == b"hi"
        # a second request on the same connection
        _send_text(sock, json.dumps({"jsonrpc": "2.0", "id": 8,
                                     "method": "test_echo", "params": [5]}).encode())
        assert json.loads(_recv_msg(sock)[1])["result"] == 10
        sock.close()
    finally:
        ws.stop()


def test_admin_namespace_over_live_node():
    pytest.importorskip("cryptography")  # AES for RLPx/discv5 paths
    from reth_tpu.net import NetworkManager, Status
    from reth_tpu.rpc.admin import AdminApi
    from reth_tpu.storage import MemDb, ProviderFactory

    factory = ProviderFactory(MemDb())
    status = Status(network_id=1, genesis=b"\x11" * 32)
    a = NetworkManager(factory, status, node_priv=0xAA1)
    b = NetworkManager(ProviderFactory(MemDb()), status, node_priv=0xBB2)
    a.start()
    b.start()
    try:
        api_a = AdminApi(a, None, chain_id=1)
        info = api_a.admin_nodeInfo()
        assert info["enode"] == a.enode
        assert info["ports"]["listener"] == a.port
        assert api_a.admin_peers() == []
        assert api_a.admin_addPeer(b.enode)
        peers = api_a.admin_peers()
        assert len(peers) == 1
        assert peers[0]["caps"] == ["eth/68", "eth/69", "snap/1"]
        assert api_a.admin_removePeer(b.enode)
        assert not api_a.admin_addPeer("enode://zz@nope")  # malformed -> False
    finally:
        a.stop()
        b.stop()
