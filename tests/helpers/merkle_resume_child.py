"""Child process for the kill -9 Merkle-resume test.

Modes:
  init     — build a deterministic chain into a durable native-KV datadir
             and run the pre-Merkle stages.
  rebuild  — run the chunked MerkleStage to completion (tiny chunks;
             MERKLE_CHILD_SLOW=1 sleeps per chunk so the parent can land
             a SIGKILL mid-rebuild). Prints RESUMED_FROM_PROGRESS when a
             prior run's progress blob was found, REBUILD_OK on success.
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from reth_tpu.primitives.keccak import keccak256_batch_np  # noqa: E402
from reth_tpu.primitives.types import Account  # noqa: E402
from reth_tpu.stages import default_stages  # noqa: E402
from reth_tpu.stages.api import ExecInput, Pipeline  # noqa: E402
from reth_tpu.stages.merkle import MerkleStage  # noqa: E402
from reth_tpu.storage.genesis import import_chain, init_genesis  # noqa: E402
from reth_tpu.storage.native import NativeDb  # noqa: E402
from reth_tpu.storage.provider import ProviderFactory  # noqa: E402
from reth_tpu.testing import ChainBuilder, Wallet  # noqa: E402
from reth_tpu.trie.committer import TrieCommitter  # noqa: E402

CPU = TrieCommitter(hasher=keccak256_batch_np)
CPU.turbo_backend = "numpy"


def build_chain():
    a = Wallet(0xAAA1)
    bld = ChainBuilder({a.address: Account(balance=10**21)}, committer=CPU)
    for blk in range(3):
        bld.build_block([
            a.transfer(bytes([blk * 16 + i + 1] * 20), 10**10 + blk * 100 + i)
            for i in range(12)
        ])
    return bld


def main():
    datadir, mode = sys.argv[1], sys.argv[2]
    factory = ProviderFactory(NativeDb(datadir))
    bld = build_chain()
    if mode == "init":
        init_genesis(factory, bld.genesis, dict(bld.accounts_at_genesis),
                     committer=CPU)
        import_chain(factory, bld.blocks[1:])
        stages = default_stages(committer=CPU)
        merkle_idx = next(
            i for i, s in enumerate(stages) if isinstance(s, MerkleStage)
        )
        Pipeline(factory, stages[:merkle_idx]).run(bld.tip.number)
        print("INIT_OK", flush=True)
        return

    with factory.provider() as p:
        if p.stage_progress(MerkleStage.id) is not None:
            print("RESUMED_FROM_PROGRESS", flush=True)
    stage = MerkleStage(CPU, chunk_leaves=3)
    target = bld.tip.number
    slow = os.environ.get("MERKLE_CHILD_SLOW") == "1"
    for _ in range(1000):
        with factory.provider_rw() as p:
            out = stage.execute(p, ExecInput(target, 0))
        if out.done:
            break
        print("CHUNK", flush=True)  # progress marker: the parent waits
        # for this before landing its SIGKILL (timing-free under load)
        if slow:
            time.sleep(0.5)
    assert out.done, "rebuild did not finish"
    print("REBUILD_OK", flush=True)


if __name__ == "__main__":
    main()
