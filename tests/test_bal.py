"""BAL parallel execution: bit-identical output to serial under
conflict-free, conflicting, coinbase-sensitive, and same-sender loads
(reference EIP-7928 + payload_processor/bal/execute.rs)."""

import numpy as np
import pytest

from reth_tpu.engine.bal import (
    BlockAccessList,
    TxAccess,
    execute_block_bal,
    record_access_list,
)
from reth_tpu.evm import BlockExecutor, EvmConfig
from reth_tpu.evm.executor import InMemoryStateSource, InvalidTransaction
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256
from reth_tpu.primitives.types import Block, Header
from reth_tpu.testing import Wallet

CFG = EvmConfig(chain_id=1)

# PUSH0 CALLDATALOAD PUSH0 SSTORE STOP
STORE_CODE = bytes.fromhex("5f355f5500")
# PUSH1 41 BALANCE POP STOP — reads the coinbase's balance (0x41... padded)
COINBASE = b"\xc0" * 20
BAL_OF_COINBASE = bytes([0x73]) + COINBASE + bytes.fromhex("315000")


def make_header(**kw):
    return Header(number=1, gas_limit=30_000_000, base_fee_per_gas=7,
                  beneficiary=COINBASE, **kw)


def setup(n_wallets=6):
    wallets = [Wallet(0x1000 + i) for i in range(n_wallets)]
    accounts = {w.address: Account(balance=10**20) for w in wallets}
    contract = b"\x5c" * 20
    accounts[contract] = Account(code_hash=keccak256(STORE_CODE))
    reader = b"\x5d" * 20
    accounts[reader] = Account(code_hash=keccak256(BAL_OF_COINBASE))
    codes = {keccak256(STORE_CODE): STORE_CODE,
             keccak256(BAL_OF_COINBASE): BAL_OF_COINBASE}
    src = InMemoryStateSource(accounts, codes=codes)
    return wallets, contract, reader, src


def run_both(src, txs, wallets_by_tx):
    senders = [w.address for w in wallets_by_tx]
    block = Block(make_header(), tuple(txs), (), ())
    serial = BlockExecutor(src, CFG).execute(block, senders)
    bal = record_access_list(src, block, senders, CFG)
    out, stats = execute_block_bal(src, block, senders, bal, CFG)
    return serial, out, stats, bal


def assert_equal_output(serial, out):
    assert [r.cumulative_gas_used for r in serial.receipts] == \
           [r.cumulative_gas_used for r in out.receipts]
    assert [r.success for r in serial.receipts] == [r.success for r in out.receipts]
    assert [r.logs for r in serial.receipts] == [r.logs for r in out.receipts]
    assert serial.gas_used == out.gas_used
    assert serial.post_accounts == out.post_accounts
    assert serial.post_storage == out.post_storage
    assert serial.changes.accounts == out.changes.accounts
    assert serial.changes.storage == out.changes.storage
    assert serial.changes.wiped_storage == out.changes.wiped_storage


def test_disjoint_transfers_parallelize():
    wallets, _, _, src = setup()
    txs = [w.transfer(bytes([0xD0 + i]) * 20, 1000 + i) for i, w in enumerate(wallets)]
    serial, out, stats, bal = run_both(src, txs, wallets)
    assert_equal_output(serial, out)
    assert stats["parallel"] == len(txs) and stats["serial"] == 0
    assert stats["waves"] == 1
    # the recorded BAL has disjoint write sets
    js = bal.to_json()
    assert len(js) == len(txs) and all(e["accountWrites"] for e in js)


def test_same_sender_chain_serializes():
    wallets, _, _, src = setup(1)
    w = wallets[0]
    txs = [w.transfer(b"\xd1" * 20, 1), w.transfer(b"\xd2" * 20, 2),
           w.transfer(b"\xd3" * 20, 3)]
    serial, out, stats, _ = run_both(src, txs, [w, w, w])
    assert_equal_output(serial, out)
    assert stats["waves"] == 3  # sender nonce chain: one per wave


def test_payment_chain_conflicts_detected():
    """A pays B, then B's balance funds B->C: read-after-write."""
    wallets, _, _, src = setup(3)
    a, b, c = wallets[0], wallets[1], wallets[2]
    txs = [a.transfer(b.address, 12345), b.transfer(c.address, 99)]
    serial, out, stats, _ = run_both(src, txs, [a, b])
    assert_equal_output(serial, out)
    assert stats["waves"] == 2


def test_storage_conflicts_and_disjoint_slots():
    wallets, contract, _, src = setup(4)
    # two writers to the SAME slot conflict; the other two hit nothing shared
    txs = [
        wallets[0].call(contract, (0xA1).to_bytes(32, "big")),
        wallets[1].call(contract, (0xA2).to_bytes(32, "big")),
        wallets[2].transfer(b"\xd7" * 20, 7),
        wallets[3].transfer(b"\xd8" * 20, 8),
    ]
    serial, out, stats, _ = run_both(src, txs, wallets[:4])
    assert_equal_output(serial, out)
    assert serial.post_storage[contract][b"\x00" * 32] == 0xA2  # later wins


def test_coinbase_sensitive_forced_serial():
    wallets, _, reader, src = setup(3)
    txs = [
        wallets[0].transfer(b"\xd1" * 20, 1),
        wallets[1].call(reader, b""),          # BALANCE(coinbase)
        wallets[2].transfer(COINBASE, 5),      # pays the fee recipient
    ]
    serial, out, stats, bal = run_both(src, txs, wallets[:3])
    assert_equal_output(serial, out)
    assert bal.entries[1].coinbase_sensitive
    assert bal.entries[2].coinbase_sensitive
    assert stats["serial"] >= 2


def test_stale_hint_falls_back_not_corrupts():
    """A WRONG access list (claims no conflicts) must still produce serial
    results — in-wave validation catches the lie."""
    wallets, _, _, src = setup(3)
    a, b, c = wallets
    txs = [a.transfer(b.address, 10**19), b.transfer(c.address, 5)]
    senders = [a.address, b.address]
    block = Block(make_header(), tuple(txs), (), ())
    serial = BlockExecutor(src, CFG).execute(block, senders)
    lying = BlockAccessList(entries=[TxAccess(index=0), TxAccess(index=1)])
    out, stats = execute_block_bal(src, block, senders, lying, CFG)
    assert_equal_output(serial, out)
    assert stats["serial"] >= 1  # the conflict was demoted, not missed


def test_invalid_block_raises_same_as_serial():
    wallets, _, _, src = setup(1)
    w = wallets[0]
    bad = w.transfer(b"\xd1" * 20, 1)  # nonce 0 twice
    bad2 = w.transfer(b"\xd1" * 20, 1)
    bad2 = Wallet(0x1000).sign_tx(
        type(bad)(**{**bad.__dict__, "nonce": 5}))  # future nonce
    block = Block(make_header(), (bad, bad2), (), ())
    senders = [w.address, w.address]
    bal = BlockAccessList(entries=[TxAccess(index=0), TxAccess(index=1)])
    with pytest.raises(InvalidTransaction):
        BlockExecutor(src, CFG).execute(block, senders)
    with pytest.raises(InvalidTransaction):
        execute_block_bal(src, block, senders, bal, CFG)


def test_engine_tree_bal_mode_reaches_same_roots():
    """An EngineTree in BAL mode validates real payloads (prewarm-recorded
    hints, wave execution) with roots identical to the builder's."""
    from reth_tpu.consensus import EthBeaconConsensus
    from reth_tpu.engine import EngineTree
    from reth_tpu.primitives.keccak import keccak256_batch_np
    from reth_tpu.storage import MemDb, ProviderFactory
    from reth_tpu.storage.genesis import init_genesis
    from reth_tpu.testing import ChainBuilder, Wallet
    from reth_tpu.trie import TrieCommitter

    CPU = TrieCommitter(hasher=keccak256_batch_np)
    wallets = [Wallet(0x2000 + i) for i in range(5)]
    builder = ChainBuilder({w.address: Account(balance=10**20) for w in wallets},
                           committer=CPU)
    # block with parallelizable + conflicting txs
    builder.build_block([w.transfer(bytes([0xE0 + i]) * 20, 100 + i)
                         for i, w in enumerate(wallets)])
    builder.build_block([wallets[0].transfer(wallets[1].address, 10**19),
                         wallets[1].transfer(wallets[2].address, 77),
                         wallets[3].transfer(b"\xe9" * 20, 1),
                         wallets[4].transfer(b"\xea" * 20, 2)])
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis, committer=CPU)
    tree = EngineTree(factory, CPU, EthBeaconConsensus(CPU),
                      bal_execution=True)
    tree.prewarm_threshold = 2
    for block in builder.blocks[1:]:
        status = tree.on_new_payload(block)
        assert status.status.name == "VALID", status.validation_error
        tree.on_forkchoice_updated(block.header.hash)
    assert tree.last_bal_stats is not None
    # genuine parallelism: multi-tx waves existed (parallel counts ONLY
    # commits from waves with >1 member)
    assert tree.last_bal_stats["parallel"] >= 2
    assert tree.last_bal_stats["waves"] < 4  # not all-singleton scheduling
