"""OverlayTx semantics: merged reads, tombstones, layer application."""

from reth_tpu.storage import MemDb
from reth_tpu.storage.overlay import OverlayTx, apply_layer


def base_db():
    db = MemDb()
    with db.tx_mut() as tx:
        tx.put("t", b"a", b"1")
        tx.put("t", b"b", b"2")
        tx.put("d", b"k", b"aaa", dupsort=True)
        tx.put("d", b"k", b"bbb", dupsort=True)
    return db


def test_read_through_and_shadow():
    db = base_db()
    ov = OverlayTx(db.tx())
    assert ov.get("t", b"a") == b"1"
    ov.put("t", b"a", b"9")
    ov.put("t", b"c", b"3")
    assert ov.get("t", b"a") == b"9"
    assert ov.get("t", b"c") == b"3"
    assert db.tx().get("t", b"a") == b"1"  # base untouched
    assert [k for k, _ in ov.cursor("t").walk()] == [b"a", b"b", b"c"]


def test_tombstone_delete():
    db = base_db()
    ov = OverlayTx(db.tx())
    assert ov.delete("t", b"a")
    assert ov.get("t", b"a") is None
    assert [k for k, _ in ov.cursor("t").walk()] == [b"b"]
    assert db.tx().get("t", b"a") == b"1"


def test_dupsort_copy_on_write():
    db = base_db()
    ov = OverlayTx(db.tx())
    ov.put("d", b"k", b"ccc", dupsort=True)
    assert ov.get_dups("d", b"k") == [b"aaa", b"bbb", b"ccc"]
    assert ov.delete("d", b"k", b"aaa")
    assert ov.get_dups("d", b"k") == [b"bbb", b"ccc"]
    assert db.tx().get_dups("d", b"k") == [b"aaa", b"bbb"]
    assert list(ov.cursor("d").walk_dup(b"k")) == [(b"k", b"bbb"), (b"k", b"ccc")]


def test_layer_stack():
    db = base_db()
    l1 = {}
    ov1 = OverlayTx(db.tx(), [], l1)
    ov1.put("t", b"a", b"L1")
    ov1.delete("t", b"b")
    ov2 = OverlayTx(db.tx(), [l1])
    assert ov2.get("t", b"a") == b"L1"
    assert ov2.get("t", b"b") is None
    ov2.put("t", b"b", b"L2")  # resurrect in upper layer
    assert ov2.get("t", b"b") == b"L2"
    assert [k for k, _ in ov2.cursor("t").walk()] == [b"a", b"b"]


def test_apply_layer_roundtrip():
    db = base_db()
    layer = {}
    ov = OverlayTx(db.tx(), [], layer)
    ov.put("t", b"a", b"new")
    ov.delete("t", b"b")
    ov.put("d", b"k", b"zzz", dupsort=True)
    ov.clear("x")  # clearing a non-existent table is fine
    with db.tx_mut() as tx:
        apply_layer(tx, layer)
    t = db.tx()
    assert t.get("t", b"a") == b"new"
    assert t.get("t", b"b") is None
    assert t.get_dups("d", b"k") == [b"aaa", b"bbb", b"zzz"]


def test_clear_table():
    db = base_db()
    ov = OverlayTx(db.tx())
    ov.clear("t")
    assert ov.get("t", b"a") is None
    assert list(ov.cursor("t").walk()) == []
    ov.put("t", b"z", b"9")
    assert [k for k, _ in ov.cursor("t").walk()] == [b"z"]
