"""Fuzz + property tests: codecs, differential hashing, sanitized C++.

Reference analogue: the reference's proptest/arbitrary codec fuzzing
(e.g. crates/storage/db codecs, eth-wire fuzz targets) and its reliance
on sanitizers for native code (SURVEY §4/§5). Deterministic seeds keep
CI stable; bump ROUNDS locally for deeper runs.
"""

import random
import subprocess
from pathlib import Path

import pytest

from reth_tpu.primitives.rlp import rlp_decode, rlp_encode
from reth_tpu.primitives.keccak import keccak256, keccak256_batch_np

ROUNDS = 300
NATIVE = Path(__file__).resolve().parent.parent / "native"


def _random_item(rng, depth=0):
    if depth > 3 or rng.random() < 0.6:
        return rng.randbytes(rng.randrange(0, 70))
    return [_random_item(rng, depth + 1) for _ in range(rng.randrange(0, 5))]


def _norm(item):
    if isinstance(item, (bytes, bytearray)):
        return bytes(item)
    return [_norm(x) for x in item]


def test_rlp_roundtrip_property():
    rng = random.Random(1)
    for _ in range(ROUNDS):
        item = _random_item(rng)
        assert _norm(rlp_decode(rlp_encode(item))) == _norm(item)


def test_rlp_decode_fuzz_never_hangs_or_crashes():
    """Arbitrary bytes: decode either succeeds or raises a clean error —
    and whatever decodes must RE-ENCODE to the exact input bytes
    (canonical-form enforcement: no two encodings for one value)."""
    rng = random.Random(2)
    for _ in range(ROUNDS):
        blob = rng.randbytes(rng.randrange(0, 120))
        try:
            item = rlp_decode(blob)
        except (ValueError, IndexError):
            continue
        assert rlp_encode(item) == blob, blob.hex()


def test_rlp_mutation_fuzz():
    """Bit-flips over valid encodings: decode must never loop or crash,
    and non-canonical mutants must be REJECTED, not reinterpreted."""
    rng = random.Random(3)
    for _ in range(ROUNDS):
        item = _random_item(rng)
        blob = bytearray(rlp_encode(item))
        if not blob:
            continue
        for _ in range(rng.randrange(1, 4)):
            blob[rng.randrange(len(blob))] ^= 1 << rng.randrange(8)
        try:
            got = rlp_decode(bytes(blob))
        except (ValueError, IndexError):
            continue
        assert rlp_encode(got) == bytes(blob)


def test_snappy_roundtrip_and_fuzz():
    from reth_tpu.net.snappy import compress, decompress

    rng = random.Random(4)
    for _ in range(ROUNDS // 3):
        # mix of compressible and random payloads, incl. empty
        if rng.random() < 0.5:
            data = rng.randbytes(rng.randrange(0, 3000))
        else:
            data = bytes(rng.choices(b"abcd", k=rng.randrange(0, 3000)))
        assert decompress(compress(data)) == data
    for _ in range(ROUNDS // 3):
        blob = rng.randbytes(rng.randrange(1, 200))
        try:
            out = decompress(blob)
            assert isinstance(out, (bytes, bytearray))
        except (ValueError, IndexError):
            pass


def test_wire_message_fuzz():
    """Random payloads into every eth message decoder: clean rejection
    or a value that re-encodes (no crashes, no type leaks)."""
    from reth_tpu.net import wire

    rng = random.Random(5)
    ids = list(wire._BY_ID)
    for _ in range(ROUNDS):
        mid = rng.choice(ids)
        blob = rng.randbytes(rng.randrange(0, 80))
        try:
            wire.decode_eth(mid, blob)
        except Exception:  # noqa: BLE001 — any CLEAN python exception is a
            pass           # correct rejection; a hang/segfault would fail CI


def test_enr_decode_fuzz():
    from reth_tpu.net.enr import Enr, EnrError, make_enr

    rng = random.Random(6)
    rec = make_enr(0xBEEF, ip="127.0.0.1", udp=1, tcp=2)
    valid = rec.encode()
    for _ in range(ROUNDS):
        blob = bytearray(valid)
        for _ in range(rng.randrange(1, 5)):
            blob[rng.randrange(len(blob))] ^= 1 << rng.randrange(8)
        try:
            got = Enr.decode(bytes(blob))
            # survivors must still verify their signature
            got.verify()
        except Exception:  # noqa: BLE001 — rejection is the expected path
            pass


def test_keccak_differential():
    """Pure-python vs vectorized numpy keccak on adversarial lengths
    (block boundaries ±1, empty, long)."""
    lengths = [0, 1, 55, 56, 135, 136, 137, 271, 272, 273, 1000]
    rng = random.Random(7)
    msgs = [rng.randbytes(n) for n in lengths]
    batched = keccak256_batch_np(msgs)
    for m, got in zip(msgs, batched):
        assert bytes(got) == keccak256(m), len(m)


def _probe_tsan(tmp: Path) -> bool:
    """gcc-12's libtsan SEGVs on 6.18+ kernels; probe before trusting it."""
    probe = tmp / "probe.cpp"
    probe.write_text("#include <thread>\nint main(){std::thread t([]{});"
                     "t.join();return 0;}\n")
    exe = tmp / "probe"
    r = subprocess.run(["g++", "-std=c++17", "-fsanitize=thread",
                        str(probe), "-o", str(exe)], capture_output=True)
    if r.returncode != 0:
        return False
    r = subprocess.run([str(exe)], capture_output=True, timeout=60)
    return r.returncode == 0


def test_sanitized_concurrent_stress(tmp_path):
    """The MVCC engine's reader/writer protocol under a sanitizer + the
    torn-snapshot detector (native/kvstore_tsan.cpp). TSAN when the
    runtime works on this kernel, ASan+UBSan otherwise."""
    use_tsan = _probe_tsan(tmp_path)
    san = "thread" if use_tsan else "address,undefined"
    exe = tmp_path / "kvstore_stress"
    r = subprocess.run(
        ["g++", "-std=c++17", "-O1", "-g", f"-fsanitize={san}",
         str(NATIVE / "kvstore.cpp"), str(NATIVE / "kvstore_tsan.cpp"),
         "-o", str(exe)],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    env = {"TSAN_OPTIONS": "halt_on_error=1",
           "ASAN_OPTIONS": "halt_on_error=1", "PATH": "/usr/bin:/bin"}
    r = subprocess.run([str(exe)], capture_output=True, text=True,
                       timeout=300, env=env)
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    assert "STRESS_OK" in r.stdout
