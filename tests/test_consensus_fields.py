"""Fork-mandated header fields: parent_beacon_block_root (Cancun, EIP-4788)
and requests_hash (Prague, EIP-7685) presence/absence gating in
consensus/validation.py — mirroring the existing blob-field checks.

With a chainspec the spec gates; without one (engine live-tip) activation
is parent-driven: once the chain carries a field it can never be dropped.
"""

from __future__ import annotations

import pytest

from reth_tpu.chainspec import (
    CANCUN,
    HARDFORK_ORDER,
    OSAKA,
    PARIS,
    PRAGUE,
    SHANGHAI,
    ChainSpec,
    ForkCondition,
)
from reth_tpu.consensus.validation import (
    ConsensusError,
    calc_next_base_fee,
    validate_header_against_parent,
)
from reth_tpu.primitives.types import Header

_EMPTY_REQUESTS = bytes.fromhex(
    "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855")


def _chainspec(cancun_ts: int | None = None,
               prague_ts: int | None = None) -> ChainSpec:
    forks = {}
    for name in HARDFORK_ORDER:
        if name == PARIS:
            forks[name] = ForkCondition(ttd=0)
        elif name == SHANGHAI:
            forks[name] = ForkCondition(timestamp=0)
        elif name == CANCUN:
            if cancun_ts is not None:
                forks[name] = ForkCondition(timestamp=cancun_ts)
        elif name == PRAGUE:
            if prague_ts is not None:
                forks[name] = ForkCondition(timestamp=prague_ts)
        elif name == OSAKA:
            continue
        else:
            forks[name] = ForkCondition(block=0)
    return ChainSpec(chain_id=1, hardforks=forks)


def _pair(parent_kw=None, child_kw=None):
    parent = Header(number=1, timestamp=1000, gas_limit=30_000_000,
                    gas_used=15_000_000, base_fee_per_gas=10**9,
                    **(parent_kw or {}))
    child_kw = dict(child_kw or {})
    child_kw.setdefault("base_fee_per_gas", calc_next_base_fee(parent))
    child = Header(number=2, parent_hash=parent.hash, timestamp=1012,
                   gas_limit=30_000_000, **child_kw)
    return parent, child


_CANCUN_FIELDS = dict(blob_gas_used=0, excess_blob_gas=0,
                      parent_beacon_block_root=b"\x00" * 32)


def test_cancun_header_valid_with_all_fields():
    parent, child = _pair(child_kw=dict(_CANCUN_FIELDS))
    validate_header_against_parent(child, parent, _chainspec(cancun_ts=0))


def test_cancun_missing_parent_beacon_root_rejected():
    kw = dict(_CANCUN_FIELDS)
    kw.pop("parent_beacon_block_root")
    parent, child = _pair(child_kw=kw)
    with pytest.raises(ConsensusError, match="missing parent beacon"):
        validate_header_against_parent(child, parent, _chainspec(cancun_ts=0))


def test_parent_beacon_root_before_cancun_rejected():
    parent, child = _pair(
        child_kw=dict(parent_beacon_block_root=b"\x00" * 32))
    with pytest.raises(ConsensusError, match="before Cancun"):
        validate_header_against_parent(child, parent, _chainspec())


def test_prague_requires_requests_hash():
    spec = _chainspec(cancun_ts=0, prague_ts=0)
    parent, child = _pair(child_kw={**_CANCUN_FIELDS,
                                    "requests_hash": _EMPTY_REQUESTS})
    validate_header_against_parent(child, parent, spec)
    parent, child = _pair(child_kw=dict(_CANCUN_FIELDS))
    with pytest.raises(ConsensusError, match="missing requests hash"):
        validate_header_against_parent(child, parent, spec)


def test_requests_hash_before_prague_rejected():
    parent, child = _pair(child_kw={**_CANCUN_FIELDS,
                                    "requests_hash": _EMPTY_REQUESTS})
    with pytest.raises(ConsensusError, match="before Prague"):
        validate_header_against_parent(child, parent, _chainspec(cancun_ts=0))


# -- chainspec-less (engine live-tip): parent-driven activation --------------


def test_no_chainspec_plain_post_merge_headers_still_pass():
    parent, child = _pair()
    validate_header_against_parent(child, parent, None)


def test_no_chainspec_beacon_root_cannot_be_dropped():
    parent, child = _pair(
        parent_kw=dict(withdrawals_root=_EMPTY_REQUESTS[:32],
                       blob_gas_used=0, excess_blob_gas=0,
                       parent_beacon_block_root=b"\x01" * 32),
        # child keeps the (parent-mandated) blob fields but drops the root
        child_kw=dict(blob_gas_used=0, excess_blob_gas=0))
    with pytest.raises(ConsensusError, match="missing parent beacon"):
        validate_header_against_parent(child, parent, None)


def test_no_chainspec_requests_hash_cannot_be_dropped():
    parent, child = _pair(
        parent_kw={**_CANCUN_FIELDS, "withdrawals_root": _EMPTY_REQUESTS[:32],
                   "requests_hash": _EMPTY_REQUESTS},
        child_kw=dict(_CANCUN_FIELDS))
    with pytest.raises(ConsensusError, match="missing requests hash"):
        validate_header_against_parent(child, parent, None)


def test_no_chainspec_activation_block_is_accepted():
    # first header to CARRY the fields (activation boundary): fine
    parent, child = _pair(
        child_kw=dict(parent_beacon_block_root=b"\x02" * 32,
                      requests_hash=_EMPTY_REQUESTS))
    validate_header_against_parent(child, parent, None)
