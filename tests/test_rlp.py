"""RLP codec tests — canonical encodings and round-trips."""

import pytest

from reth_tpu.primitives.rlp import rlp_encode, rlp_decode, encode_int


CASES = [
    (b"", "80"),
    (b"\x00", "00"),
    (b"\x0f", "0f"),
    (b"\x7f", "7f"),
    (b"\x80", "8180"),
    (b"dog", "83646f67"),
    ([], "c0"),
    ([b"cat", b"dog"], "c88363617483646f67"),
    # nested: [ [], [[]], [ [], [[]] ] ]
    ([[], [[]], [[], [[]]]], "c7c0c1c0c3c0c1c0"),
    (b"a" * 55, "b7" + "61" * 55),
    (b"a" * 56, "b838" + "61" * 56),
]


@pytest.mark.parametrize("item,expect", CASES)
def test_canonical(item, expect):
    assert rlp_encode(item).hex() == expect


@pytest.mark.parametrize("item,_", CASES)
def test_roundtrip(item, _):
    assert rlp_decode(rlp_encode(item)) == item


def test_encode_int():
    assert encode_int(0) == b""
    assert encode_int(15) == b"\x0f"
    assert encode_int(1024) == b"\x04\x00"
    assert rlp_encode(encode_int(0)).hex() == "80"


def test_reject_noncanonical():
    with pytest.raises(ValueError):
        rlp_decode(bytes.fromhex("8100"))  # single byte <0x80 must be bare
    with pytest.raises(ValueError):
        rlp_decode(bytes.fromhex("8180") + b"x")  # trailing bytes


def test_long_list_roundtrip():
    item = [b"x" * 30, [b"y" * 40, b"z"], b""] * 5
    assert rlp_decode(rlp_encode(item)) == item
