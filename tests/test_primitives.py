"""Tests for nibbles, types, and secp256k1 sender recovery."""

import numpy as np

from reth_tpu.primitives import (
    Account,
    Header,
    Transaction,
    EMPTY_ROOT_HASH,
    KECCAK_EMPTY,
)
from reth_tpu.primitives.nibbles import (
    unpack_nibbles,
    pack_nibbles,
    encode_path,
    decode_path,
    common_prefix_len,
)
from reth_tpu.primitives.types import Receipt, Log, Block, Withdrawal
from reth_tpu.primitives import secp256k1


def test_constants():
    assert EMPTY_ROOT_HASH.hex() == "56e81f171bcc55a6ff8345e692c0f86e5b48e01b996cadc001622fb5e363b421"
    assert KECCAK_EMPTY.hex() == "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470"


def test_nibbles_roundtrip():
    key = bytes(range(32))
    nibs = unpack_nibbles(key)
    assert len(nibs) == 64
    assert pack_nibbles(nibs) == key


def test_hex_prefix():
    # yellow paper examples
    assert encode_path(bytes([1, 2, 3, 4, 5]), False).hex() == "112345"
    assert encode_path(bytes([0, 1, 2, 3, 4, 5]), False).hex() == "00012345"
    assert encode_path(bytes([0, 15, 1, 12, 11, 8]), True).hex() == "200f1cb8"
    assert encode_path(bytes([15, 1, 12, 11, 8]), True).hex() == "3f1cb8"
    for nibs in [b"", bytes([5]), bytes([1, 2, 3]), bytes(range(10))]:
        for leaf in (False, True):
            assert decode_path(encode_path(nibs, leaf)) == (nibs, leaf)


def test_common_prefix():
    assert common_prefix_len(bytes([1, 2, 3]), bytes([1, 2, 4])) == 2
    assert common_prefix_len(b"", bytes([1])) == 0


def test_account_roundtrip():
    acc = Account(nonce=3, balance=10**18)
    assert Account.trie_decode(acc.trie_encode()) == acc
    assert Account().is_empty
    assert not Account(balance=1).is_empty


def test_header_roundtrip():
    h = Header(number=100, base_fee_per_gas=7, withdrawals_root=EMPTY_ROOT_HASH,
               blob_gas_used=0, excess_blob_gas=0, parent_beacon_block_root=b"\x11" * 32)
    assert Header.decode(h.encode()) == h
    assert len(h.hash) == 32
    # pre-london header (no optionals)
    h0 = Header(number=1)
    assert Header.decode(h0.encode()) == h0


def test_sign_and_recover():
    priv = 0xA11CE
    addr = secp256k1.address_from_priv(priv)
    tx = Transaction(tx_type=2, chain_id=1, nonce=0, max_fee_per_gas=10**9,
                     max_priority_fee_per_gas=10**8, gas_limit=21000,
                     to=b"\x22" * 20, value=10**17)
    parity, r, s = secp256k1.sign(tx.signing_hash(), priv)
    signed = Transaction(**{**tx.__dict__, "y_parity": parity, "r": r, "s": s})
    assert signed.recover_sender() == addr
    # encode/decode round trip preserves sender
    assert Transaction.decode(signed.encode()) == signed


def test_legacy_tx_roundtrip():
    priv = 0xB0B
    tx = Transaction(tx_type=0, chain_id=1, nonce=5, gas_price=2 * 10**9,
                     gas_limit=21000, to=b"\x33" * 20, value=123)
    parity, r, s = secp256k1.sign(tx.signing_hash(), priv)
    signed = Transaction(**{**tx.__dict__, "y_parity": parity, "r": r, "s": s})
    assert Transaction.decode(signed.encode()) == signed
    assert signed.recover_sender() == secp256k1.address_from_priv(priv)


def test_invalid_legacy_v_rejected():
    import pytest
    from reth_tpu.primitives.rlp import rlp_encode
    # v=1 is not a valid legacy signature v (must be 27/28 or >=35)
    raw = rlp_encode([b"", b"", b"", b"", b"", b"", b"\x01", b"\x01", b"\x01"])
    with pytest.raises(ValueError, match="invalid legacy signature v"):
        Transaction.decode(raw)


def test_noncanonical_hex_prefix_rejected():
    import pytest
    with pytest.raises(ValueError):
        decode_path(bytes.fromhex("45"))  # flag nibble 4 invalid
    with pytest.raises(ValueError):
        decode_path(bytes.fromhex("0f12"))  # even path with nonzero pad nibble


def test_receipt_and_bloom():
    log = Log(address=b"\x01" * 20, topics=(b"\x02" * 32,), data=b"xyz")
    r = Receipt(tx_type=2, success=True, cumulative_gas_used=21000, logs=(log,))
    enc = r.encode_2718()
    assert enc[0] == 2
    bloom = r.bloom()
    assert len(bloom) == 256
    assert any(bloom)  # some bits set
    assert Receipt().bloom() == b"\x00" * 256


def test_block_roundtrip():
    h = Header(number=7, base_fee_per_gas=10, withdrawals_root=EMPTY_ROOT_HASH)
    tx = Transaction(tx_type=2, chain_id=1, to=b"\x01" * 20, r=1, s=1)
    blk = Block(header=h, transactions=(tx,),
                withdrawals=(Withdrawal(0, 1, b"\x02" * 20, 10),))
    assert Block.decode(blk.encode()) == blk
