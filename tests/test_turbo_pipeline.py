"""Overlapped rebuild pipeline (trie/turbo.py RebuildPipeline): parity,
packing, arena residency, fault drills, and the threaded native sweep.

The pipeline must be bit-identical to the serial turbo path it overlaps:
pooled `native/triebuild.cpp` sweeps + cross-subtrie level packing +
resident digest arena may change WHEN rows hash, never WHAT they hash.
Roots and TrieUpdates branch metadata are pinned against
``commit_hashed_many`` (itself pinned to the Python oracle by
tests/test_turbo_commit.py).
"""

from __future__ import annotations

import os
import subprocess
from pathlib import Path

import numpy as np
import pytest

from reth_tpu.primitives.rlp import rlp_encode
from reth_tpu.trie.turbo import (
    DigestArena,
    RebuildPipeline,
    TurboCommitter,
    _group_jobs,
    _NumpyBackend,
)

NATIVE = Path(__file__).resolve().parent.parent / "native"


def _job(n, seed, val_len=(1, 100)):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, 256, size=(n, 32), dtype=np.uint8)
    keys = np.unique(keys.view("S32").ravel()).view(np.uint8).reshape(-1, 32)
    rng.shuffle(keys)
    values = [
        rlp_encode(bytes(rng.integers(0, 256, size=int(rng.integers(*val_len)),
                                      dtype=np.uint8)))
        for _ in range(len(keys))
    ]
    return keys, values


def _prefix_jobs(n, seed):
    """Merkle-chunk-shaped jobs: the account trie split into two-nibble
    prefix subtries, committed at start_depth=2 (_account_chunk's shape)."""
    keys, values = _job(n, seed)
    jobs = []
    for pfx in np.unique(keys[:, 0]):
        sel = np.nonzero(keys[:, 0] == pfx)[0]
        jobs.append((keys[sel], [values[i] for i in sel]))
    return jobs


@pytest.fixture(scope="module")
def turbo_np():
    return TurboCommitter(backend="numpy")


# -- parity ------------------------------------------------------------------


@pytest.mark.parametrize("knobs", [
    dict(jobs_per_sweep=1, pack_window=1),       # no packing, max overlap
    dict(jobs_per_sweep=4, pack_window=16),      # grouped sweeps, wide packs
    dict(jobs_per_sweep=64, leaves_per_sweep=200),  # leaf-bounded groups
    dict(hash_workers=3),                        # parallel window hashing
])
def test_pipelined_root_and_branch_parity(turbo_np, knobs):
    jobs = [_job(30 + 17 * i, seed=i) for i in range(12)]
    want = turbo_np.commit_hashed_many(jobs, collect_branches=True)
    got = turbo_np.commit_hashed_pipelined(jobs, collect_branches=True, **knobs)
    assert [r.root for r in got] == [r.root for r in want]
    for g, w in zip(got, want):
        assert g.branch_nodes == w.branch_nodes


def test_pipelined_subtrie_start_depth_parity(turbo_np):
    """The chunked Merkle rebuild's exact call shape: prefix subtries at
    start_depth=2, branch paths subtrie-relative."""
    jobs = _prefix_jobs(600, seed=7)
    want = [turbo_np.commit_hashed_many([j], collect_branches=True,
                                        start_depth=2)[0] for j in jobs]
    got = turbo_np.commit_hashed_pipelined(jobs, collect_branches=True,
                                           start_depth=2, jobs_per_sweep=8)
    assert [r.root for r in got] == [r.root for r in want]
    for g, w in zip(got, want):
        assert g.branch_nodes == w.branch_nodes


def test_pipelined_empty_and_single(turbo_np):
    from reth_tpu.primitives.types import EMPTY_ROOT_HASH

    assert turbo_np.commit_hashed_pipelined([]) == []
    # <=1 job short-circuits to the serial path
    one = turbo_np.commit_hashed_pipelined([_job(40, seed=3)])
    assert one[0].root == turbo_np.commit_hashed_many([_job(40, seed=3)])[0].root
    mixed = turbo_np.commit_hashed_pipelined(
        [(np.zeros((0, 32), dtype=np.uint8), []), _job(5, seed=1)])
    assert mixed[0].root == EMPTY_ROOT_HASH


def test_pipeline_env_kill_switch(turbo_np, monkeypatch):
    """RETH_TPU_PIPELINE=0 forces the serial path — the A/B switch bench.py
    uses; both must agree regardless."""
    monkeypatch.setenv("RETH_TPU_PIPELINE", "0")
    jobs = [_job(25, seed=i) for i in range(6)]
    got = turbo_np.commit_hashed_pipelined(jobs)
    want = turbo_np.commit_hashed_many(jobs)
    assert [r.root for r in got] == [r.root for r in want]


def test_pipelined_rejects_like_serial(turbo_np):
    """Oversized leaf values reject in the sweep — the same ValueError the
    MerkleStage catches to fall back to the general committer."""
    keys, values = _job(8, seed=2)
    values[3] = b"\xb9\xff\xff" + bytes(65535)  # > native leaf cap
    with pytest.raises(ValueError, match="oversized"):
        turbo_np.commit_hashed_pipelined(
            [(keys, values), _job(10, seed=4)], jobs_per_sweep=1)


# -- grouping / packing ------------------------------------------------------


def test_group_jobs_bounds():
    jobs = [(None, [b""] * n) for n in (10, 10, 10, 50, 5, 5)]
    # leaf bound splits after the job that crosses it; job bound caps width
    assert _group_jobs(jobs, max_leaves=20, max_jobs=64) == [
        (0, 2), (2, 4), (4, 6)]
    assert _group_jobs(jobs, max_leaves=10**9, max_jobs=2) == [
        (0, 2), (2, 4), (4, 6)]
    assert _group_jobs([], 100, 4) == []


def test_pipeline_metrics_recorded(turbo_np):
    from reth_tpu.metrics import pipeline_metrics

    jobs = [_job(30, seed=40 + i) for i in range(8)]
    turbo_np.commit_hashed_pipelined(jobs, jobs_per_sweep=2)
    last = pipeline_metrics.last
    assert last is not None
    assert last["jobs"] == 8 and last["groups"] == 4
    assert last["windows"] >= 1 and last["backend"] == "numpy"
    assert last["queue_peak"] >= 1 and last["drained_windows"] == 0
    for k in ("sweep_s", "pack_s", "dispatch_s", "fetch_s"):
        assert last[k] >= 0.0


# -- resident digest arena ---------------------------------------------------


def test_arena_resident_across_commits():
    arena = DigestArena()
    b = _NumpyBackend(arena=arena)
    b.begin(100)
    first = b._buf
    assert first is arena.digest_buf(1)      # backend writes the arena buf
    b.ensure(50)
    assert b._buf is first                   # within capacity: no realloc
    b.ensure(5000)
    grown = b._buf
    assert grown.shape[0] >= 5001 and arena.grows == 1
    b2 = _NumpyBackend(arena=arena)          # next commit, same arena
    b2.begin(100)
    assert b2._buf is grown                  # resident: reused, not realloc'd


def test_arena_growth_preserves_digests():
    arena = DigestArena()
    b = _NumpyBackend(arena=arena)
    b.begin(10)
    s = b.alloc_slot()
    b._buf[s] = 0xAB
    b.ensure(100_000)
    assert bytes(b._buf[s]) == b"\xab" * 32


def test_arena_rows_thread_local():
    import threading

    arena = DigestArena()
    bufs = {}

    def grab(name):
        r = arena.rows(4, 16)
        r[:] = 1
        bufs[name] = arena.rows(4, 16)

    t = threading.Thread(target=grab, args=("worker",))
    t.start(); t.join()
    grab("main")
    assert bufs["main"].base is not bufs["worker"].base  # never shared


# -- fault drills ------------------------------------------------------------


def test_injected_pipeline_abort(turbo_np, monkeypatch):
    """RETH_TPU_FAULT_PIPELINE_ABORT kills the commit at a window boundary
    — the in-process crash-mid-queue drill the resume test builds on."""
    from reth_tpu.ops.supervisor import InjectedPipelineAbort

    monkeypatch.setenv("RETH_TPU_FAULT_PIPELINE_ABORT", "2")
    jobs = [_job(20, seed=60 + i) for i in range(8)]
    with pytest.raises(InjectedPipelineAbort, match="window #2"):
        turbo_np.commit_hashed_pipelined(jobs, jobs_per_sweep=1, pack_window=1)
    # the wounded committer must still complete the next (clean) commit
    monkeypatch.delenv("RETH_TPU_FAULT_PIPELINE_ABORT")
    got = turbo_np.commit_hashed_pipelined(jobs, jobs_per_sweep=1)
    want = turbo_np.commit_hashed_many(jobs)
    assert [r.root for r in got] == [r.root for r in want]


def test_mid_pipeline_failover_drains_onto_cpu():
    """Wedge every device dispatch under the supervised ('auto') route: the
    pipeline keeps feeding the failed-over backend, the queue drains onto
    the numpy twin, and the roots still match the oracle."""
    from reth_tpu.metrics import MetricsRegistry, pipeline_metrics
    from reth_tpu.ops.supervisor import DeviceSupervisor, FaultInjector, ProbeResult

    def probe(budget, injector=None):
        return ProbeResult(True, 0.001, None)

    sup = DeviceSupervisor(dispatch_budget=120.0, probe_fn=probe,
                           registry=MetricsRegistry(),
                           injector=FaultInjector(wedge_every=1))
    auto = TurboCommitter(backend="auto", min_tier=64, supervisor=sup)
    jobs = [_job(40, seed=80 + i) for i in range(10)]
    want = TurboCommitter(backend="numpy").commit_hashed_many(jobs)
    got = auto.commit_hashed_pipelined(jobs, jobs_per_sweep=2)
    assert [r.root for r in got] == [r.root for r in want]
    assert sup.failovers >= 1
    last = pipeline_metrics.last
    assert last["backend"] == "numpy"        # effective plane after the trip
    assert last["drained_windows"] >= 1      # windows hashed post-failover


# -- threaded native sweep under a sanitizer ---------------------------------


def _probe_tsan(tmp: Path) -> bool:
    """gcc-12's libtsan SEGVs on 6.18+ kernels; probe before trusting it."""
    probe = tmp / "probe.cpp"
    probe.write_text("#include <thread>\nint main(){std::thread t([]{});"
                     "t.join();return 0;}\n")
    exe = tmp / "probe"
    r = subprocess.run(["g++", "-std=c++17", "-fsanitize=thread",
                        str(probe), "-o", str(exe)], capture_output=True)
    if r.returncode != 0:
        return False
    r = subprocess.run([str(exe)], capture_output=True, timeout=60)
    return r.returncode == 0


@pytest.mark.slow
def test_triebuild_threaded_stress(tmp_path):
    """The pipeline calls rtb_build from a thread pool: run the real access
    pattern (shared read-only arrays, concurrent handles) under TSAN
    (ASan+UBSan where libtsan breaks on the running kernel) and require
    deterministic per-round results — native/triebuild_tsan.cpp."""
    use_tsan = _probe_tsan(tmp_path)
    san = "thread" if use_tsan else "address,undefined"
    exe = tmp_path / "triebuild_stress"
    r = subprocess.run(
        ["g++", "-std=c++17", "-O1", "-g", f"-fsanitize={san}",
         str(NATIVE / "triebuild.cpp"), str(NATIVE / "triebuild_tsan.cpp"),
         "-o", str(exe)],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    env = {"TSAN_OPTIONS": "halt_on_error=1",
           "ASAN_OPTIONS": "halt_on_error=1", "PATH": "/usr/bin:/bin"}
    r = subprocess.run([str(exe)], capture_output=True, text=True,
                       timeout=300, env=env)
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    assert "STRESS_OK" in r.stdout


def test_pipeline_concurrent_sweeps_deterministic(turbo_np):
    """Python-level rerun determinism: many small groups racing through the
    pool must always produce the same roots."""
    jobs = [_job(15, seed=200 + i) for i in range(16)]
    runs = [
        [r.root for r in turbo_np.commit_hashed_pipelined(
            jobs, jobs_per_sweep=1, pack_window=2)]
        for _ in range(3)
    ]
    assert runs[0] == runs[1] == runs[2]
