"""Device warm-up manager drills (reth_tpu/ops/warmup.py).

The acceptance drills: with RETH_TPU_FAULT_COMPILE_WEDGE forcing shape
compiles past their watchdog budget, the node serves DEGRADED on the CPU
twin (bit-identical digests), compiles retry with exponential backoff, the
circuit breaker trips instead of startup freezing, and shapes promote to
the device once the fault clears. The persistent compilation cache is
validated end-to-end in subprocesses (the probe's opt-in cache mode), and
a corrupted cache entry quarantines + rebuilds rather than crashing.
Everything runs CPU-only (JAX_PLATFORMS=cpu via conftest) — the injector
stands in for the wedged tunnel, which is the point: the compile lifecycle
must be testable without hardware.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from reth_tpu.metrics import MetricsRegistry, compile_tracker
from reth_tpu.ops.fused_commit import FusedLevelEngine, _Bucket
from reth_tpu.ops.keccak_jax import _CPU_BUCKET, KeccakDevice, _next_tier
from reth_tpu.ops.supervisor import (
    CLOSED,
    OPEN,
    CircuitBreaker,
    DeviceSupervisor,
    FaultInjector,
    ProbeResult,
    probe_device,
)
from reth_tpu.ops.warmup import (
    COLD,
    FAILED,
    WARM,
    CompileCache,
    MenuShape,
    WarmupManager,
    build_warmup,
    default_menu,
    kernel_source_digest,
)
from reth_tpu.primitives.keccak import keccak256, keccak256_batch_np
from reth_tpu.trie.committer import TrieCommitter


def _ok_probe(budget, injector=None, **kw):
    return ProbeResult(True, 0.001)


def _supervisor(**kw):
    kw.setdefault("dispatch_budget", 120.0)
    kw.setdefault("probe_fn", _ok_probe)
    kw.setdefault("registry", MetricsRegistry())
    return DeviceSupervisor(**kw)


def _mgr(menu=None, builder=None, **kw):
    kw.setdefault("registry", MetricsRegistry())
    kw.setdefault("budget", 0.25)
    kw.setdefault("attempts", 2)
    kw.setdefault("backoff", 0.01)
    kw.setdefault("verify_cache", False)
    kw.setdefault("enable_cache", False)  # never touch global jax config
    if menu is None:
        menu = [MenuShape("keccak.masked", 4, 8),
                MenuShape("keccak.masked", 8, 8)]
    if builder is None:
        builder = lambda shape: None  # noqa: E731
    return WarmupManager(menu=menu, builder=builder, **kw)


def _msgs(n, size=40, seed=0):
    rng = np.random.default_rng(seed)
    return [bytes(rng.integers(0, 256, size, dtype=np.uint8))
            for _ in range(n)]


# -- shape menu ---------------------------------------------------------------


def test_default_menu_grid():
    menu = default_menu(min_tier=1024, block_tier=4, max_batch_tier=16384,
                        max_block_tier=32)
    keys = [s.key() for s in menu]
    assert len(keys) == len(set(keys))
    # batch ladder for trie-node-sized messages
    for t in (1024, 2048, 4096, 8192, 16384):
        assert ("keccak.masked", 4, t, 1) in keys
    # block ladder for large messages at the base tier
    for bt in (8, 16, 32):
        assert ("keccak.masked", bt, 1024, 1) in keys
    # fused level-commit programs
    assert ("fused.plain", 4, 1024, 1) in keys
    assert ("fused.splice", 4, 1024, 1) in keys
    # ceilings respected
    assert all(s.batch_tier <= 16384 and s.block_tier <= 32 for s in menu)
    assert default_menu(include_fused=False) == [
        s for s in menu if not s.program.startswith("fused")]


def test_default_menu_mesh_variants():
    """mesh_sizes adds SPMD menu slots whose tiers sit on the
    device-count-multiple ladder (what MeshKeccak/FusedMeshEngine mint)."""
    menu = default_menu(min_tier=1024, mesh_sizes=(8,))
    keys = [s.key() for s in menu]
    assert len(keys) == len(set(keys))
    for t in (1024, 2048, 4096, 8192, 16384):
        assert ("keccak.masked", 4, t, 8) in keys
    assert ("fused.plain", 4, 1024, 8) in keys
    assert ("fused.splice", 4, 1024, 8) in keys
    # a non-pow2 mesh rounds the floor up to a device-count multiple
    menu6 = default_menu(min_tier=1024, mesh_sizes=(6,))
    mesh6 = [s for s in menu6 if s.mesh_size == 6]
    assert mesh6 and all(s.batch_tier % 6 == 0 for s in mesh6)
    assert ("fused.plain", 4, 1026, 6) in [s.key() for s in mesh6]
    assert str(mesh6[0]).endswith("@m6")


def test_next_tier_clamps_to_menu_ceiling():
    assert _next_tier(5, 8) == 8
    assert _next_tier(100, 8) == 128
    assert _next_tier(100_000, 8, max_tier=1024) == 1024
    assert _next_tier(100, 8, max_tier=1024) == 128


# -- persistent compilation cache ---------------------------------------------


def test_kernel_source_digest_versions_cache_dir(tmp_path):
    a = tmp_path / "a.py"
    b = tmp_path / "b.py"
    a.write_text("kernel v1")
    b.write_text("kernel v1")
    d1 = kernel_source_digest([a])
    assert d1 == kernel_source_digest([a])  # deterministic
    a.write_text("kernel v2")
    assert kernel_source_digest([a]) != d1  # source edit -> new cache dir
    assert kernel_source_digest([b]) == d1  # same bytes -> same digest
    cc1 = CompileCache(tmp_path / "cache", sources=[a])
    cc2 = CompileCache(tmp_path / "cache", sources=[b])
    assert cc1.dir != cc2.dir
    assert cc1.dir.parent == cc2.dir.parent


def test_cache_validate_healthy_preserves_entries(tmp_path):
    cc = CompileCache(tmp_path, sources=[])
    cc.dir.mkdir(parents=True)
    (cc.dir / "entry-1").write_bytes(b"x" * 64)
    (cc.dir / "entry-2").write_bytes(b"y" * 64)
    rep = cc.validate()
    assert rep == {"entries": 2, "corrupt": 0, "quarantined": False}
    assert cc.entry_count() == 2
    assert cc.summary()["mode"] == "off"  # not enabled yet


def test_cache_corruption_quarantines_and_rebuilds(tmp_path):
    cc = CompileCache(tmp_path, sources=[])
    cc.dir.mkdir(parents=True)
    (cc.dir / "good").write_bytes(b"x" * 64)
    (cc.dir / "truncated").write_bytes(b"")  # zero-length = corrupt
    rep = cc.validate()
    assert rep["quarantined"] and rep["corrupt"] == 1 and rep["entries"] == 0
    # the fresh dir exists and is empty; the old one was moved aside
    assert cc.dir.is_dir() and cc.entry_count() == 0
    quarantined = list(tmp_path.glob("*.quarantine-*"))
    assert len(quarantined) == 1
    assert (quarantined[0] / "good").read_bytes() == b"x" * 64
    # a second corruption quarantines under a distinct name
    (cc.dir / "bad").write_bytes(b"")
    assert cc.validate()["quarantined"]
    assert len(list(tmp_path.glob("*.quarantine-*"))) == 2


def test_probe_cache_validation_mode_end_to_end(tmp_path):
    """The opt-in probe mode: the child runs WITH jax_compilation_cache_dir
    set, proving the persistent cache loads — and actually persists entries
    on disk, so a second (restart-shaped) probe starts warm."""
    cc = CompileCache(tmp_path, sources=[])
    cc.validate()
    r1 = probe_device(120, cache_dir=str(cc.dir))
    assert r1.ok, r1.diag
    assert cc.entry_count() > 0  # the compile landed on disk
    entries = cc.entry_count()
    r2 = probe_device(120, cache_dir=str(cc.dir))  # warm restart
    assert r2.ok, r2.diag
    assert cc.entry_count() == entries  # loaded, nothing recompiled
    assert cc.probe()  # the CompileCache wrapper agrees


def test_cache_enable_disable_round_trip(tmp_path):
    import jax

    cc = CompileCache(tmp_path, sources=[])
    cc.validate()
    try:
        assert cc.enable()
        assert jax.config.jax_compilation_cache_dir == str(cc.dir)
        assert cc.summary()["mode"] == "cold"  # enabled, no entries yet
    finally:
        cc.disable()
    assert jax.config.jax_compilation_cache_dir is None
    assert not cc.enabled


# -- manager lifecycle --------------------------------------------------------


def test_happy_path_all_shapes_warm():
    built = []
    mgr = _mgr(builder=built.append)
    assert mgr.overall_state() == "off"
    snap = mgr.run()
    assert [s.key() for s in built] == [s.key() for s in mgr.menu]
    assert snap["state"] == "warm"
    assert snap["warm"] == snap["total"] == 2 and snap["failed"] == 0
    assert mgr.device_ready()
    assert mgr.route_bucket("keccak.masked", 4, 8)
    # fully warm: off-menu stragglers are allowed (watchdog covers them)
    assert mgr.route_bucket("keccak.masked", 64, 8)
    assert mgr.cpu_routed == 0
    assert all(s == WARM for s in mgr.states.values())


def test_no_gating_before_start():
    mgr = _mgr()
    assert mgr.device_ready()
    assert mgr.route_bucket("keccak.masked", 4, 8)
    assert mgr.route_bucket("anything", 1, 1)
    assert mgr.cpu_routed == 0


def test_degraded_routing_while_warming():
    mgr = _mgr()
    mgr._active = True  # mid-warm-up: nothing compiled yet
    assert not mgr.device_ready()
    assert not mgr.route_bucket("keccak.masked", 4, 8)
    assert mgr.cpu_routed == 1
    # per-shape promotion: ONE shape warming routes ITS buckets to the
    # device while the sibling still serves on the CPU twin
    mgr.states[("keccak.masked", 4, 8, 1)] = WARM
    assert mgr.route_bucket("keccak.masked", 4, 8)
    assert not mgr.route_bucket("keccak.masked", 8, 8)
    assert mgr.cpu_routed == 2
    assert mgr.overall_state() == "warming"


def test_background_start_and_wait():
    slow = threading.Event()

    def builder(shape):
        slow.wait(2.0)

    mgr = _mgr(builder=builder)
    mgr.start()
    assert not mgr.device_ready()  # warming in the background
    slow.set()
    assert mgr.wait(5.0)
    assert mgr.device_ready()
    mgr.start()  # idempotent once done (thread not alive)
    assert mgr.device_ready()


def test_compile_wedge_drill_budget_retry_then_warm():
    """RETH_TPU_FAULT_COMPILE_WEDGE=1: the first compile wedges PAST the
    watchdog budget (real join-timeout path), the retry succeeds."""
    inj = FaultInjector(compile_wedge=1)
    mgr = _mgr(menu=[MenuShape("keccak.masked", 4, 8)], injector=inj,
               budget=0.1, attempts=3, backoff=0.01)
    t0 = time.monotonic()
    snap = mgr.run()
    assert snap["state"] == "warm"
    assert mgr.wedges == 1 and mgr.retries == 1
    assert inj.compiles_wedged == 1 and inj.compile_wedge == 0
    # the wedged attempt burned ~the budget, not the injected sleep
    assert time.monotonic() - t0 < 1.5


def test_compile_wedge_forever_trips_breaker_and_degrades():
    """The full drill: every compile wedges -> shapes FAIL after bounded
    retries, the supervisor's breaker OPENS (startup never freezes), and
    serving is degraded to the CPU twin."""
    inj = FaultInjector(compile_wedge=-1)
    breaker = CircuitBreaker(failure_threshold=2, reset_timeout=0.05)
    sup = _supervisor(breaker=breaker, injector=inj)
    mgr = _mgr(menu=[MenuShape("keccak.masked", 4, 8)], supervisor=sup,
               injector=inj, budget=0.05, attempts=2, backoff=0.01)
    assert sup.warmup is mgr  # attached at construction
    snap = mgr.run()
    assert snap["state"] == "degraded" and snap["failed"] == 1
    assert mgr.states[("keccak.masked", 4, 8, 1)] == FAILED
    assert breaker.state == OPEN  # wedges fed the breaker
    assert not mgr.device_ready()
    assert not sup.warmup_allows_device()
    assert not mgr.route_bucket("keccak.masked", 4, 8)


def test_promotion_after_fault_clears_via_half_open_probe():
    """Recovery: the fault clears, the breaker's half-open probe succeeds,
    and on_device_recovered promotes the FAILED shapes."""
    inj = FaultInjector(compile_wedge=-1)
    breaker = CircuitBreaker(failure_threshold=2, reset_timeout=0.05)
    sup = _supervisor(breaker=breaker, injector=inj)
    mgr = _mgr(menu=[MenuShape("keccak.masked", 4, 8)], supervisor=sup,
               injector=inj, budget=0.05, attempts=2, backoff=0.01)
    mgr.run()
    assert breaker.state == OPEN and not mgr.device_ready()
    with inj._lock:
        inj.compile_wedge = 0  # the wedge clears
    time.sleep(0.06)  # past the breaker cooldown -> next route half-opens
    assert sup.allows_device()  # half-open probe ok -> closes + promotes
    for _ in range(200):
        if mgr.device_ready():
            break
        time.sleep(0.01)
    assert mgr.device_ready()
    assert mgr.states[("keccak.masked", 4, 8, 1)] == WARM
    assert breaker.state == CLOSED
    assert sup.warmup_allows_device()


def test_breaker_open_defers_without_burning_attempts():
    sup = _supervisor()
    sup.breaker.force_open()

    def builder(shape):  # pragma: no cover - must not run
        raise AssertionError("compile attempted while breaker open")

    mgr = _mgr(menu=[MenuShape("keccak.masked", 4, 8)], supervisor=sup,
               builder=builder)
    snap = mgr.run()
    assert snap["state"] == "degraded"
    assert mgr.states[("keccak.masked", 4, 8, 1)] == FAILED
    assert mgr.wedges == 0  # deferred, not wedged


def test_retry_failed_reentrancy_guard():
    calls = []
    mgr = _mgr(menu=[MenuShape("keccak.masked", 4, 8)],
               builder=calls.append, attempts=1)
    mgr._active = True
    mgr.states[("keccak.masked", 4, 8, 1)] = FAILED
    with mgr._lock:
        mgr._retrying = True
    assert mgr.retry_failed() == 0  # guarded
    with mgr._lock:
        mgr._retrying = False
    assert mgr.retry_failed() == 1
    assert len(calls) == 1


def test_fault_injector_env_and_active():
    inj = FaultInjector.from_env({"RETH_TPU_FAULT_COMPILE_WEDGE": "2"})
    assert inj is not None and inj.compile_wedge == 2 and inj.active()
    t0 = time.monotonic()
    inj.on_compile(0.01)
    inj.on_compile(0.01)
    assert inj.compiles_wedged == 2 and inj.compile_wedge == 0
    inj.on_compile(0.01)  # exhausted: no wedge
    assert inj.compiles_wedged == 2
    assert time.monotonic() - t0 < 5
    assert FaultInjector.from_env({}) is None


# -- degraded-mode serving through the real dispatch front-ends ---------------


def test_keccak_device_degraded_buckets_bit_identical():
    msgs = _msgs(5)
    expect = keccak256_batch_np(msgs)
    mgr = _mgr(menu=[MenuShape("keccak.masked", 4, 8)])
    dev = KeccakDevice(min_tier=8, block_tier=4, warmup=mgr)
    assert dev.hash_batch(msgs) == expect  # not started: device route
    mgr._active = True  # warming: CPU twin, same digests
    assert dev.hash_batch(msgs) == expect
    assert mgr.cpu_routed >= 1
    routed = mgr.cpu_routed
    mgr.states[("keccak.masked", 4, 8, 1)] = WARM  # promoted mid-warm-up
    assert dev.hash_batch(msgs) == expect
    assert mgr.cpu_routed == routed  # warm shape went to the device


def test_supervised_hasher_picks_up_attached_warmup():
    sup = _supervisor()
    mgr = _mgr(supervisor=sup)
    committer = TrieCommitter(supervisor=sup)
    committer.attach_warmup(mgr)
    assert committer.warmup is mgr
    assert committer.hasher._warmup is mgr
    msgs = _msgs(4)
    mgr._active = True  # degraded: buckets on the CPU twin
    assert committer.hasher(msgs) == keccak256_batch_np(msgs)
    assert mgr.cpu_routed >= 1


def test_attach_warmup_reaches_plain_keccak_device():
    committer = TrieCommitter(min_tier=8)
    mgr = _mgr()
    committer.attach_warmup(mgr)
    assert committer.hasher.__self__.warmup is mgr


def test_supervised_backend_fused_commit_gated_until_warm():
    from reth_tpu.primitives.nibbles import unpack_nibbles
    from reth_tpu.primitives.rlp import rlp_encode

    leaves = [(unpack_nibbles(keccak256(bytes([i]))),
               rlp_encode(b"v%d" % i)) for i in range(40)]
    expect = TrieCommitter(hasher=keccak256_batch_np).commit(leaves).root

    sup = _supervisor()
    mgr = _mgr(supervisor=sup)
    mgr._active = True  # warming
    committer = TrieCommitter(fused=True, min_tier=16, supervisor=sup)
    res = committer.commit(leaves)
    assert res.root == expect
    assert committer._engine.effective_kind == "numpy"  # degraded commit
    mgr.run()  # everything warms
    res = committer.commit(leaves)
    assert res.root == expect
    assert committer._engine.effective_kind == "device"


# -- tier clamps (keccak_jax + fused_commit mirrors) --------------------------


def test_oversized_batch_chunked_at_menu_ceiling():
    before = set(compile_tracker.shapes)
    dev = KeccakDevice(min_tier=8, max_batch_tier=16)
    assert dev.max_batch_tier == 16
    msgs = _msgs(50)
    assert dev.hash_batch(msgs) == keccak256_batch_np(msgs)
    minted = set(compile_tracker.shapes) - before
    assert all(shape[-1] <= 16 for shape in minted)  # no tier above ceiling


def test_max_batch_tier_normalized_onto_ladder():
    dev = KeccakDevice(min_tier=8, max_batch_tier=100)
    assert dev.max_batch_tier == 64  # largest pow2 ladder step <= 100


def test_block_ceiling_routes_to_cpu_twin_no_new_program():
    before = set(compile_tracker.shapes)
    dev = KeccakDevice(min_tier=8, block_tier=4, max_block_tier=8)
    big = bytes(range(256)) * 8  # 2048 B = 16 rate blocks > ceiling 8
    small = _msgs(3)
    msgs = [small[0], big, small[1], big + b"!", small[2]]
    assert dev._bucket_key(16) == _CPU_BUCKET
    assert dev.hash_batch(msgs) == keccak256_batch_np(msgs)
    assert dev.hash_batch([big])[0] == keccak256(big)
    minted = set(compile_tracker.shapes) - before
    assert all(shape[1] <= 8 for shape in minted)  # no over-ceiling program


def test_fused_block_tier_ceiling_raises():
    eng = FusedLevelEngine(min_tier=8)
    eng.begin(4)
    bucket = _Bucket()
    giant = bytes(70 * 136 - 10)  # 70 rate blocks > MAX_BLOCK_TIER=64
    bucket.add(giant, 70, 1, [])
    with pytest.raises(ValueError, match="block-tier ceiling"):
        eng.dispatch_level(bucket)
    with pytest.raises(ValueError, match="block-tier ceiling"):
        eng.dispatch_packed(np.zeros(16, np.uint8),
                            np.zeros(1, np.uint32), np.full(1, 8, np.uint32),
                            np.ones(1, np.int32), None, 128)


def test_fused_row_cap_splits_level_bit_identical():
    from reth_tpu.primitives.nibbles import unpack_nibbles
    from reth_tpu.primitives.rlp import rlp_encode

    leaves = [(unpack_nibbles(keccak256(b"k%d" % i)),
               rlp_encode(b"value-%d" % i)) for i in range(120)]
    expect = TrieCommitter(hasher=keccak256_batch_np).commit(leaves).root
    committer = TrieCommitter(fused=True, min_tier=16)
    committer._engine.MAX_BATCH_ROWS = 16  # force menu-cap splitting
    assert committer._engine._row_cap() == 16
    assert committer.commit(leaves).root == expect


# -- observability ------------------------------------------------------------


def test_metrics_and_snapshot_surface(tmp_path):
    reg = MetricsRegistry()
    cc = CompileCache(tmp_path, sources=[])
    mgr = _mgr(registry=reg, cache=cc)
    snap = mgr.run()
    out = reg.render()
    assert "# TYPE warmup_state gauge" in out
    assert "warmup_shapes_total 2" in out
    assert "warmup_shapes_warm 2" in out
    assert "warmup_compiles_total 2.0" in out
    assert "warmup_compile_seconds_bucket" in out
    assert snap["cache"]["mode"] == "off"  # verify_cache=False: not enabled
    assert snap["compile_wall_s"] >= 0
    assert snap["shapes"] == {"keccak.masked:4x8": WARM,
                              "keccak.masked:8x8": WARM}
    assert snap["compiling"] is None


def test_supervisor_snapshot_carries_warmup_state():
    sup = _supervisor()
    assert sup.snapshot()["warmup"] is None
    mgr = _mgr(supervisor=sup)
    assert sup.snapshot()["warmup"] == "off"
    mgr.run()
    assert sup.snapshot()["warmup"] == "warm"


def test_events_line_has_warmup_fragment():
    from reth_tpu.node.events import CanonUpdate, NodeEventReporter

    class _Stub:
        pool = None
        network = None
        hasher_supervisor = None
        hash_service = None
        gateway = None
        warmup = None

    node = _Stub()
    node.warmup = _mgr()
    node.warmup._active = True
    rep = NodeEventReporter(node)
    rep._tip = CanonUpdate(1, b"\x11" * 32, 0, 0)
    rep._blocks = 1
    line = rep.report_once()
    assert "warmup[warming 0/2" in line
    node.warmup.run()
    rep._tip = CanonUpdate(2, b"\x22" * 32, 0, 0)
    rep._blocks = 1
    line = rep.report_once()
    assert "warmup[warm 2/2 cache=off" in line


def test_build_warmup_constructor(tmp_path):
    sup = _supervisor()
    mgr = build_warmup(supervisor=sup, cache_dir=tmp_path / "cc",
                       registry=MetricsRegistry(),
                       menu=[MenuShape("keccak.masked", 4, 8)],
                       builder=lambda s: None, verify_cache=False)
    assert mgr.sup is sup and sup.warmup is mgr
    assert mgr.cache is not None and mgr.cache.base == tmp_path / "cc"
    assert build_warmup(registry=MetricsRegistry()).cache is None


# -- kill-and-restart drill ---------------------------------------------------


def test_restart_with_populated_cache_reports_hits(tmp_path):
    """Second 'node start' against the same persistent cache dir: every
    shape compile finds its entry already on disk and the warmup line
    reports cache hits with a near-zero marginal entry count."""
    cc = CompileCache(tmp_path, sources=[])
    entries = {"n": 0}

    def builder(shape):
        # first run writes one cache entry per shape; the restart writes
        # nothing (the loader served it) — modelled via the entry counter
        # the manager samples around each compile
        if entries["n"] < 2:
            (cc.dir / f"entry-{entries['n']}").write_bytes(b"x" * 32)
            entries["n"] += 1

    menu = [MenuShape("keccak.masked", 4, 8), MenuShape("keccak.masked", 8, 8)]
    mgr1 = WarmupManager(menu=menu, cache=cc, builder=builder,
                         verify_cache=False, enable_cache=False,
                         registry=MetricsRegistry(),
                         budget=1, attempts=1, backoff=0.01)
    cc.enabled = True  # unit scope: skip the jax config global
    snap1 = mgr1.run()
    assert snap1["state"] == "warm"
    assert snap1["cache_misses"] == 2 and snap1["cache_hits"] == 0

    cc2 = CompileCache(tmp_path, sources=[])
    cc2.validate()
    assert cc2.entry_count() == 2  # survived the "restart"
    mgr2 = WarmupManager(menu=menu, cache=cc2, builder=lambda s: None,
                         verify_cache=False, enable_cache=False,
                         registry=MetricsRegistry(),
                         budget=1, attempts=1, backoff=0.01)
    cc2.enabled = True
    snap2 = mgr2.run()
    assert snap2["state"] == "warm"
    assert snap2["cache_hits"] == 2 and snap2["cache_misses"] == 0
    assert snap2["cache"]["mode"] == "warm"


# -- bench integration --------------------------------------------------------


def test_bench_emits_warmup_state_and_cache_fields(tmp_path):
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               RETH_TPU_BENCH_MODE="gateway",
               RETH_TPU_BENCH_GW_CLIENTS="2",
               RETH_TPU_BENCH_GW_REQS="4",
               RETH_TPU_BENCH_GW_KEYS="2",
               RETH_TPU_BENCH_GW_WORK="4",
               RETH_TPU_BENCH_TIMEOUT="300",
               # keep the repo's trailing perf-baseline store out of
               # test runs (tiny workloads would poison real vs_prev)
               RETH_TPU_BENCH_BASELINE_STORE=str(
                   tmp_path / "baselines.json"))
    env.pop("RETH_TPU_WARMUP", None)
    env.pop("RETH_TPU_COMPILE_CACHE_DIR", None)
    repo = Path(__file__).resolve().parent.parent
    r = subprocess.run([sys.executable, str(repo / "bench.py")],
                       capture_output=True, text=True, timeout=280,
                       cwd=str(repo), env=env)
    assert r.returncode == 0, r.stderr[-800:]
    line = json.loads(r.stdout.strip().splitlines()[-1])
    assert "warmup_state" in line and "compile_cache" in line
    assert "compile_wall_s" in line and "compiled_shapes" in line
    assert line["value"] > 0
