"""MVCC snapshot isolation: concurrent reader/writer stress on all KV
backends (VERDICT round-1 weak #5 — historical reads racing a writer).

Invariant under test: the writer commits batches that keep `sum` ==
sum of all `k:*` values in one atomic publish; any reader transaction
must observe a consistent pair no matter when it starts or how long it
iterates. Pre-MVCC this raced (readers saw live mutations mid-commit).
"""

from __future__ import annotations

import threading

import pytest

from reth_tpu.storage.kv import MemDb
from reth_tpu.storage.native import NativeDb, PagedDb

BATCHES = 60
KEYS = 40


def _make(backend, tmp_path):
    if backend == "mem":
        return MemDb()
    if backend == "paged":
        return PagedDb(str(tmp_path / "paged"))
    return NativeDb(str(tmp_path / "native"))


def _writer(db, stop):
    for i in range(1, BATCHES + 1):
        with db.tx_mut() as tx:
            total = 0
            for k in range(KEYS):
                v = i * 1000 + k
                total += v
                tx.put("t", b"k%03d" % k, v.to_bytes(8, "big"))
            tx.put("t", b"sum", total.to_bytes(8, "big"))
    stop.set()


def _reader(db, stop, errors):
    while True:
        tx = db.tx()
        try:
            s = tx.get("t", b"sum")
            if s is not None:
                declared = int.from_bytes(s, "big")
                got = 0
                n = 0
                for k, v in tx.cursor("t").walk():
                    if k.startswith(b"k"):
                        got += int.from_bytes(v, "big")
                        n += 1
                if n != KEYS or got != declared:
                    errors.append(
                        f"inconsistent snapshot: n={n} got={got} declared={declared}"
                    )
                    return
        finally:
            tx.abort()
        if stop.is_set():
            return


@pytest.mark.parametrize("backend", ["mem", "native", "paged"])
def test_concurrent_reader_writer_snapshots(tmp_path, backend):
    db = _make(backend, tmp_path)
    stop = threading.Event()
    errors: list[str] = []
    readers = [threading.Thread(target=_reader, args=(db, stop, errors))
               for _ in range(3)]
    w = threading.Thread(target=_writer, args=(db, stop))
    for t in readers:
        t.start()
    w.start()
    w.join(timeout=120)
    for t in readers:
        t.join(timeout=30)
        assert not t.is_alive(), "reader thread wedged"
    assert not errors, errors[:3]


@pytest.mark.parametrize("backend", ["mem", "native", "paged"])
def test_reader_snapshot_stable_across_commit(tmp_path, backend):
    """A read txn opened BEFORE a commit must keep seeing the old state."""
    db = _make(backend, tmp_path)
    with db.tx_mut() as tx:
        tx.put("t", b"a", b"1")
    reader = db.tx()
    assert reader.get("t", b"a") == b"1"
    with db.tx_mut() as tx:
        tx.put("t", b"a", b"2")
        tx.put("t", b"b", b"3")
        tx.clear("u")
    # the reader's view is frozen at its begin
    assert reader.get("t", b"a") == b"1"
    assert reader.get("t", b"b") is None
    assert [k for k, _ in reader.cursor("t").walk()] == [b"a"]
    reader.abort()
    fresh = db.tx()
    assert fresh.get("t", b"a") == b"2"
    assert fresh.get("t", b"b") == b"3"
    fresh.abort()


@pytest.mark.parametrize("backend", ["mem", "native", "paged"])
def test_abort_discards_all_writes(tmp_path, backend):
    db = _make(backend, tmp_path)
    with db.tx_mut() as tx:
        tx.put("t", b"x", b"keep")
    tx = db.tx_mut()
    tx.put("t", b"x", b"changed")
    tx.put("t", b"y", b"new")
    tx.clear("t")
    tx.put("t", b"z", b"after-clear")
    tx.abort()
    check = db.tx()
    assert check.get("t", b"x") == b"keep"
    assert check.get("t", b"y") is None
    assert check.get("t", b"z") is None
    check.abort()
