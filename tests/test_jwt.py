"""Engine-port JWT auth (HS256): token validation + HTTP rejection e2e."""

from __future__ import annotations

import json
import os
import time
import urllib.error
import urllib.request

import pytest

from reth_tpu.rpc.jwt import (
    IAT_WINDOW,
    JwtError,
    encode_jwt,
    load_or_create_secret,
    validate_jwt,
)
from reth_tpu.rpc.server import RpcServer

SECRET = bytes(range(32))


def test_jwt_roundtrip():
    token = encode_jwt(SECRET, {"sub": "cl"})
    claims = validate_jwt(SECRET, token)
    assert claims["sub"] == "cl"
    assert abs(claims["iat"] - time.time()) < 5


def test_jwt_rejections():
    token = encode_jwt(SECRET)
    with pytest.raises(JwtError, match="signature"):
        validate_jwt(b"\x00" * 32, token)
    with pytest.raises(JwtError, match="malformed"):
        validate_jwt(SECRET, "nope")
    stale = encode_jwt(SECRET, {"iat": int(time.time()) - IAT_WINDOW - 10})
    with pytest.raises(JwtError, match="iat"):
        validate_jwt(SECRET, stale)
    # tampered payload
    h, p, s = token.split(".")
    with pytest.raises(JwtError):
        validate_jwt(SECRET, f"{h}.{p}x.{s}")


def test_secret_file_roundtrip(tmp_path):
    path = tmp_path / "jwt.hex"
    s1 = load_or_create_secret(path)
    assert len(s1) == 32
    assert load_or_create_secret(path) == s1  # stable across restarts
    path.write_text("0x" + "ab" * 32)
    assert load_or_create_secret(path) == b"\xab" * 32


def _post(port, token=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}",
        data=json.dumps({"jsonrpc": "2.0", "id": 1, "method": "test_ping",
                         "params": []}).encode(),
        headers={"Authorization": f"Bearer {token}"} if token else {},
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.status, json.loads(r.read())


def test_http_auth_enforcement():
    server = RpcServer(jwt_secret=SECRET)
    server.register_method("test_ping", lambda: "pong")
    port = server.start()
    try:
        # no token -> 401
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(port)
        assert e.value.code == 401
        assert "unauthorized" in json.loads(e.value.read())["error"]["message"]
        # wrong secret -> 401
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(port, encode_jwt(os.urandom(32)))
        assert e.value.code == 401
        # valid token -> 200
        status, resp = _post(port, encode_jwt(SECRET))
        assert status == 200 and resp["result"] == "pong"
    finally:
        server.stop()


def test_http_open_without_secret():
    server = RpcServer()
    server.register_method("test_ping", lambda: "pong")
    port = server.start()
    try:
        status, resp = _post(port)
        assert status == 200 and resp["result"] == "pong"
    finally:
        server.stop()
