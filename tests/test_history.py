"""History index stages + HistoricalStateProvider + historical RPC."""

from reth_tpu.consensus import EthBeaconConsensus
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.stages import Pipeline, default_stages
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.storage.genesis import import_chain, init_genesis
from reth_tpu.storage.historical import HistoricalStateProvider
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)

STORE_CODE = bytes.fromhex("5f355f5500")  # sstore(0, calldata[0])


def initcode_for(runtime: bytes) -> bytes:
    n = len(runtime)
    return bytes([0x60, n, 0x60, 0x0B, 0x5F, 0x39, 0x60, n, 0x5F, 0xF3]) + b"\x00" + runtime


def build_env():
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)}, committer=CPU)
    from reth_tpu.primitives.keccak import keccak256
    from reth_tpu.primitives.rlp import encode_int, rlp_encode

    contract = keccak256(rlp_encode([alice.address, encode_int(0)]))[12:]
    builder.build_block([alice.deploy(initcode_for(STORE_CODE))])          # 1
    builder.build_block([alice.call(contract, (0x11).to_bytes(32, "big"))])  # 2
    builder.build_block([alice.transfer(b"\x0b" * 20, 777)])               # 3
    builder.build_block([alice.call(contract, (0x22).to_bytes(32, "big"))])  # 4
    builder.build_block([])                                                # 5
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis, committer=CPU)
    import_chain(factory, builder.blocks[1:], EthBeaconConsensus(CPU))
    pipeline = Pipeline(factory, default_stages(committer=CPU))
    pipeline.run(5)
    return factory, builder, alice.address, contract, pipeline


def test_historical_account_values():
    factory, builder, alice_addr, contract, _ = build_env()
    p = factory.provider()
    # nonce history: 0 at genesis, 1 after block 1, ... 4 after block 4
    for block, want_nonce in [(0, 0), (1, 1), (2, 2), (3, 3), (4, 4), (5, 4)]:
        hist = HistoricalStateProvider(p, block)
        acc = hist.account(alice_addr)
        assert (acc.nonce if acc else 0) == want_nonce, f"block {block}"
    # bob funded at block 3
    bob = b"\x0b" * 20
    assert HistoricalStateProvider(p, 2).account(bob) is None
    assert HistoricalStateProvider(p, 3).account(bob).balance == 777


def test_historical_storage_values():
    factory, builder, alice_addr, contract, _ = build_env()
    p = factory.provider()
    slot = b"\x00" * 32
    assert HistoricalStateProvider(p, 1).storage(contract, slot) == 0
    assert HistoricalStateProvider(p, 2).storage(contract, slot) == 0x11
    assert HistoricalStateProvider(p, 3).storage(contract, slot) == 0x11
    assert HistoricalStateProvider(p, 4).storage(contract, slot) == 0x22
    assert HistoricalStateProvider(p, 5).storage(contract, slot) == 0x22


def test_history_unwind():
    factory, builder, alice_addr, contract, pipeline = build_env()
    pipeline.unwind(2)
    p = factory.provider()
    # indices reflect only blocks <= 2 now
    from reth_tpu.stages.index_history import first_change_after
    from reth_tpu.storage.tables import Tables

    assert first_change_after(p, Tables.AccountsHistory.name, alice_addr, 2) is None
    # resync rebuilds them
    pipeline.run(5)
    p = factory.provider()
    assert HistoricalStateProvider(p, 3).account(b"\x0b" * 20).balance == 777


def test_shard_splitting():
    from reth_tpu.stages.index_history import SHARD_CAP, _append_to_shards, first_change_after
    from reth_tpu.storage.tables import Tables

    factory = ProviderFactory(MemDb())
    with factory.provider_rw() as p:
        _append_to_shards(p, Tables.AccountsHistory.name, b"\xaa" * 20,
                          list(range(1, SHARD_CAP * 2 + 50)))
        # lookups cross shard boundaries correctly
        assert first_change_after(p, Tables.AccountsHistory.name, b"\xaa" * 20, 0) == 1
        assert first_change_after(p, Tables.AccountsHistory.name, b"\xaa" * 20,
                                  SHARD_CAP) == SHARD_CAP + 1
        assert first_change_after(p, Tables.AccountsHistory.name, b"\xaa" * 20,
                                  SHARD_CAP * 2 + 49) is None


def test_historical_via_engine_persistence():
    """Blocks persisted by the ENGINE (not the pipeline) are indexed too,
    and the unindexed in-memory window is served via the changeset tail."""
    from reth_tpu.engine import EngineTree
    from reth_tpu.rpc import EthApi
    from reth_tpu.rpc.convert import data, parse_qty

    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)}, committer=CPU)
    for i in range(5):
        builder.build_block([alice.transfer(b"\x0b" * 20, 100 + i)])
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis, committer=CPU)
    tree = EngineTree(factory, committer=CPU, persistence_threshold=2)
    for blk in builder.blocks[1:]:
        assert tree.on_new_payload(blk).status.value == "VALID"
        tree.on_forkchoice_updated(blk.hash)
    assert tree.persisted_number == 3  # 4,5 in memory
    api = EthApi(tree, None, 1)
    bob = data(b"\x0b" * 20)
    # indexed range (persisted blocks)
    assert parse_qty(api.eth_getBalance(bob, "0x1")) == 100
    assert parse_qty(api.eth_getBalance(bob, "0x2")) == 201
    # unindexed in-memory window via changeset tail scan
    assert parse_qty(api.eth_getBalance(bob, "0x4")) == 100 + 101 + 102 + 103
    # unknown block rejected
    import pytest as _pytest
    from reth_tpu.rpc import RpcError

    with _pytest.raises(RpcError):
        api.eth_getBalance(bob, "0x63")


def test_historical_rpc_balance():
    from reth_tpu.engine import EngineTree
    from reth_tpu.rpc import EthApi
    from reth_tpu.rpc.convert import data, parse_qty

    factory, builder, alice_addr, contract, _ = build_env()
    tree = EngineTree(factory, committer=CPU)
    api = EthApi(tree, None, 1)
    bal_b2 = parse_qty(api.eth_getBalance(data(b"\x0b" * 20), "0x2"))
    bal_b3 = parse_qty(api.eth_getBalance(data(b"\x0b" * 20), "0x3"))
    assert (bal_b2, bal_b3) == (0, 777)
    slot_b2 = api.eth_getStorageAt(data(contract), "0x0", "0x2")
    assert parse_qty(slot_b2) == 0x11
