"""Bit-exactness tests for the CPU keccak reference implementations."""

import os

import numpy as np
import pytest

from reth_tpu.primitives.keccak import (
    keccak256,
    keccak256_batch_np,
    RATE,
)

# Known Keccak-256 vectors (Ethereum keccak, NOT NIST SHA3).
VECTORS = [
    (b"", "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470"),
    (b"abc", "4e03657aea45a94fc7d47ba826c8d667c0d1e6e33a64a036ec44f58fa12d6c45"),
    (b"hello", "1c8aff950685c2ed4bc3174f3472287b56d9517b9c948127319a09a7a36deac8"),
    (
        b"The quick brown fox jumps over the lazy dog",
        "4d741b6f1eb29cb2a9b9911c82f56fa8d73b04959d3d9d222895df6c0b28aa15",
    ),
]


@pytest.mark.parametrize("msg,expect", VECTORS)
def test_known_vectors(msg, expect):
    assert keccak256(msg).hex() == expect


def test_boundary_lengths():
    """Exercise padding at rate boundaries (135/136/137 bytes etc.)."""
    rng = np.random.default_rng(0)
    for ln in [0, 1, 55, 56, RATE - 2, RATE - 1, RATE, RATE + 1, 2 * RATE - 1, 2 * RATE, 300, 1000]:
        msg = bytes(rng.integers(0, 256, size=ln, dtype=np.uint8))
        # batch impl must agree with the pure reference
        assert keccak256_batch_np([msg])[0] == keccak256(msg), f"len={ln}"


def test_batch_mixed_lengths_order_preserved():
    rng = np.random.default_rng(1)
    msgs = [bytes(rng.integers(0, 256, size=int(l), dtype=np.uint8))
            for l in rng.integers(0, 500, size=64)]
    got = keccak256_batch_np(msgs)
    want = [keccak256(m) for m in msgs]
    assert got == want


def test_empty_batch():
    assert keccak256_batch_np([]) == []
