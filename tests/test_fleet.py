"""Stateless read-replica fleet (reth_tpu/fleet/): witness feed framing,
the consistent-hash ring + router draining ladder, replica serving
bit-identical to the full node, and the kill-mid-load chaos drills."""

import json
import socket
import threading
import time
import urllib.request

import pytest

from reth_tpu.fleet.feed import (
    FEED_MAGIC,
    FeedError,
    recv_frame,
    send_frame,
)
from reth_tpu.fleet.replica import ReplicaFaultInjector, ReplicaNode
from reth_tpu.fleet.ring import FleetRouter, HashRing
from reth_tpu.metrics import MetricsRegistry
from reth_tpu.primitives.keccak import keccak256, keccak256_batch_np
from reth_tpu.primitives.rlp import encode_int, rlp_encode
from reth_tpu.primitives.types import Account
from reth_tpu.rpc.server import RpcServer
from reth_tpu.testing import Wallet
from reth_tpu.trie.committer import TrieCommitter


# -- consistent-hash ring -----------------------------------------------------


def test_ring_deterministic_and_distinct_failover_order():
    r = HashRing(vnodes=32)
    for n in ("a", "b", "c"):
        r.add(n)
    key = b"gateway-cache-key"
    order = list(r.nodes_for(key))
    assert sorted(order) == ["a", "b", "c"]
    assert order == list(r.nodes_for(key))  # stable
    assert len(set(order)) == 3             # distinct failover order


def test_ring_minimal_disruption_on_membership_change():
    r = HashRing(vnodes=64)
    for n in ("a", "b", "c", "d"):
        r.add(n)
    keys = [str(i).encode() for i in range(400)]
    before = {k: next(r.nodes_for(k)) for k in keys}
    r.remove("d")
    after = {k: next(r.nodes_for(k)) for k in keys}
    # only keys that lived on the removed node remap
    assert all(after[k] == before[k] for k in keys if before[k] != "d")
    assert any(before[k] == "d" for k in keys)
    # re-adding restores the original mapping exactly
    r.add("d")
    assert all(next(r.nodes_for(k)) == before[k] for k in keys)


def test_ring_empty_and_single():
    r = HashRing()
    assert list(r.nodes_for(b"x")) == []
    r.add("only")
    assert list(r.nodes_for(b"x")) == ["only"]
    r.remove("only")
    assert list(r.nodes_for(b"x")) == []


# -- feed framing -------------------------------------------------------------


def test_feed_frame_roundtrip_and_corruption():
    a, b = socket.socketpair()
    try:
        payload = {"type": "block", "number": 7, "blob": b"\x00" * 1000}
        send_frame(a, payload)
        assert recv_frame(b) == payload
        # CRC corruption: flip a payload byte behind a valid header
        send_frame(a, {"x": 1})
        raw = bytearray(b.recv(65536))
        raw[-1] ^= 0xFF
        c, d = socket.socketpair()
        try:
            c.sendall(bytes(raw))
            with pytest.raises(FeedError, match="CRC"):
                recv_frame(d)
        finally:
            c.close()
            d.close()
        # torn tail: a peer dying mid-frame is a clean ConnectionError
        e, f = socket.socketpair()
        try:
            import pickle
            import struct
            import zlib

            payload = pickle.dumps({"z": 3})
            frame = struct.pack("<II", len(payload),
                                zlib.crc32(payload)) + payload
            e.sendall(frame[:len(frame) // 2])
            e.close()
            with pytest.raises(ConnectionError):
                recv_frame(f)
        finally:
            f.close()
    finally:
        for s in (a, b):
            try:
                s.close()
            except OSError:
                pass


def test_replica_fault_injector_env():
    assert ReplicaFaultInjector.from_env(env={}) is None
    inj = ReplicaFaultInjector.from_env(
        env={"RETH_TPU_FAULT_REPLICA_WEDGE": "1"})
    assert inj is not None and inj.wedge and not inj.lag_s
    assert inj.on_block(1) is True and inj.dropped == 1
    inj = ReplicaFaultInjector.from_env(
        env={"RETH_TPU_FAULT_REPLICA_LAG": "0.01"})
    assert inj is not None and inj.lag_s == 0.01 and not inj.wedge
    assert inj.on_block(1) is False and inj.lagged == 1


# -- router draining / failover over fake replicas ----------------------------


class _FakeReplica:
    """A plain RpcServer masquerading as a replica: canned fleet_status
    + an eth_call handler, enough for the router's probe and routing."""

    def __init__(self, result="0xfake", lag=0, wedged=False):
        self.result = result
        self.status = {"head": {"number": 5, "hash": "0x00"},
                       "lag_heads": lag, "wedged": wedged,
                       "connected": True}
        self.calls = 0
        self.srv = RpcServer()
        self.srv.register_method("fleet_status", lambda: self.status)
        self.srv.register_method("eth_call", self._call)
        self.port = self.srv.start()
        self.url = f"http://127.0.0.1:{self.port}"

    def _call(self, *params):
        self.calls += 1
        return self.result

    def stop(self):
        self.srv.stop()


def test_router_routes_stably_and_fails_over_to_local():
    router = FleetRouter(probe_interval=0, registry=MetricsRegistry())
    reps = [_FakeReplica(result=f"0x{i}") for i in range(2)]
    try:
        for r in reps:
            router.register(r.url)
        key = ("eth_call", "[]", b"head")
        local_calls = []
        out1 = router.route("eth_call", [], key, lambda: local_calls.append(1))
        out2 = router.route("eth_call", [], key, lambda: local_calls.append(1))
        # same key -> same replica, and the local node was never touched
        assert out1 == out2 and not local_calls
        assert router.routed == 2
        # a different key may land elsewhere but still on a replica
        out3 = router.route("eth_call", [], ("eth_call", "[1]", b"head"),
                            lambda: local_calls.append(1))
        assert out3 in ("0x0", "0x1") and not local_calls
    finally:
        for r in reps:
            r.stop()
        router.stop()


def test_router_sheds_dead_replica_and_falls_back_local():
    router = FleetRouter(probe_interval=0, registry=MetricsRegistry())
    rep = _FakeReplica()
    rid = router.register(rep.url)
    rep.stop()  # transport-dead
    out = router.route("eth_call", [], ("eth_call", "[]", b"h"),
                       lambda: "local-answer")
    assert out == "local-answer"
    assert router.local_fallbacks == 1 and router.failovers == 1
    snap = router.snapshot()
    assert snap["healthy"] == 0
    assert snap["replicas"][0]["state"] == "unreachable"
    # probe keeps it out of the ring while dead
    router.probe_once()
    assert router.snapshot()["healthy"] == 0
    router.deregister(rid)


def test_router_probe_drains_on_lag_and_wedge_then_heals():
    router = FleetRouter(probe_interval=0, max_lag=2, heal_n=1,
                         registry=MetricsRegistry())
    rep = _FakeReplica(lag=5)
    try:
        router.register(rep.url)
        router.probe_once()
        snap = router.snapshot()
        assert snap["healthy"] == 0
        assert snap["replicas"][0]["state"] == "draining"
        assert "lag" in snap["replicas"][0]["last_error"]
        # recovery: lag drops -> heal_n good probes re-admit it
        rep.status["lag_heads"] = 0
        router.probe_once()
        assert router.snapshot()["healthy"] == 1
        assert router.heals == 1
        # wedged flag sheds regardless of lag
        rep.status["wedged"] = True
        router.probe_once()
        assert router.snapshot()["replicas"][0]["state"] == "draining"
    finally:
        rep.stop()
        router.stop()


def test_router_replica_error_fails_over_without_shedding():
    router = FleetRouter(probe_interval=0, registry=MetricsRegistry())

    class _Erroring(_FakeReplica):
        def _call(self, *params):
            self.calls += 1
            from reth_tpu.rpc.server import RpcError

            raise RpcError(-32001, "state not in witness")

    rep = _Erroring()
    try:
        router.register(rep.url)
        out = router.route("eth_call", [], ("eth_call", "[]", b"h"),
                           lambda: "local")
        assert out == "local"
        assert rep.calls == 1
        # a witness miss is a failover, not a shed: the replica stays in
        snap = router.snapshot()
        assert snap["healthy"] == 1 and router.failovers == 1
    finally:
        rep.stop()
        router.stop()


# -- end-to-end: fleet node + live replicas -----------------------------------

# PUSH1 32 CALLDATALOAD (value) PUSH0 CALLDATALOAD (key) SSTORE STOP:
# a kvstore writing storage[calldata[0:32]] = calldata[32:64]
KV_CODE = bytes.fromhex("6020355f355500")
# PUSH0 PUSH0 LOG0 STOP: emits one empty log
LOG_CODE = bytes.fromhex("5f5fa000")


def _initcode(runtime: bytes) -> bytes:
    n = len(runtime)
    return bytes([0x60, n, 0x60, 0x0B, 0x5F, 0x39, 0x60, n, 0x5F, 0xF3]) \
        + b"\x00" + runtime


def _create_address(sender: bytes, nonce: int) -> bytes:
    return keccak256(rlp_encode([sender, encode_int(nonce)]))[12:]


def _kv_set(wallet, kv, key: int, value: int):
    data = key.to_bytes(32, "big") + value.to_bytes(32, "big")
    return wallet.call(kv, data)


def _rpc(port, method, params):
    body = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                       "params": params}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/", data=body,
        headers={"Content-Type": "application/json"})
    return json.loads(urllib.request.urlopen(req, timeout=15).read())


@pytest.fixture(scope="module")
def fleet_env():
    """A dev full node in fleet mode + one synced replica: blocks carry
    transfers, kvstore storage writes, and a log-emitting call."""
    from reth_tpu.node import Node, NodeConfig
    from reth_tpu.testing import ChainBuilder

    committer = TrieCommitter(hasher=keccak256_batch_np)
    committer.turbo_backend = "numpy"
    wallet = Wallet(0xF1EE7)
    builder = ChainBuilder({wallet.address: Account(balance=10**21)},
                           committer=committer)
    node = Node(NodeConfig(dev=True, genesis_header=builder.genesis,
                           genesis_alloc=builder.accounts_at_genesis,
                           fleet=True, http_port=0, authrpc_port=0),
                committer=committer)
    node.fleet_router.probe_interval = 0  # probed explicitly
    http, _ = node.start_rpc()
    fport = node.feed_server.port
    replica = ReplicaNode("127.0.0.1", fport, registry=MetricsRegistry(),
                          replica_id="t-replica")
    rport = replica.start()

    kv = _create_address(wallet.address, 0)
    logger = _create_address(wallet.address, 1)
    sink = b"\x0b" * 20
    blocks = [
        [wallet.deploy(_initcode(KV_CODE)),
         wallet.deploy(_initcode(LOG_CODE))],
        [_kv_set(wallet, kv, 1, 0xA1), _kv_set(wallet, kv, 2, 0xB2),
         _kv_set(wallet, kv, 3, 0xC3)],
        [wallet.call(logger, b""), wallet.transfer(sink, 1000)],
        # n+1 deletes a key that collapses into a sibling the previous
        # block's witness never revealed — the closure path, live
        [_kv_set(wallet, kv, 2, 0)],
    ]
    for i, txs in enumerate(blocks):
        for tx in txs:
            node.pool.add_transaction(tx)
        node.miner.mine_block(timestamp=1_700_000_000 + i * 12)
    assert replica.wait_synced(len(blocks), timeout=60), feed_diag(node)
    node.fleet_router.register(f"http://127.0.0.1:{rport}")
    node.fleet_router.probe_once()
    env = {"node": node, "replica": replica, "wallet": wallet,
           "http": http, "rport": rport, "kv": kv, "logger": logger,
           "sink": sink, "tip": len(blocks), "fport": fport}
    yield env
    replica.stop()
    node.stop()


def feed_diag(node):
    return f"feed: {node.feed_server.snapshot()}"


def test_replica_validates_with_zero_failures(fleet_env):
    r = fleet_env["replica"]
    assert r.blocks_validated == fleet_env["tip"]
    assert r.validation_failures == 0
    assert r.lag_heads() == 0
    st = r.status()
    assert st["connected"] and not st["wedged"]
    assert st["window"] == [1, fleet_env["tip"]]


def test_replica_blocks_bit_identical(fleet_env):
    http, rport, tip = (fleet_env[k] for k in ("http", "rport", "tip"))
    for n in range(1, tip + 1):
        for full in (False, True):
            a = _rpc(http, "eth_getBlockByNumber", [hex(n), full])
            b = _rpc(rport, "eth_getBlockByNumber", [hex(n), full])
            assert a["result"] == b["result"]
    h = _rpc(http, "eth_getBlockByNumber", [hex(tip), False])["result"]["hash"]
    a = _rpc(http, "eth_getBlockByHash", [h, True])
    b = _rpc(rport, "eth_getBlockByHash", [h, True])
    assert a["result"] == b["result"]


def test_replica_calls_bit_identical(fleet_env):
    http, rport = fleet_env["http"], fleet_env["rport"]
    wallet, sink = fleet_env["wallet"], fleet_env["sink"]
    calls = [
        {"from": "0x" + wallet.address.hex(), "to": "0x" + sink.hex(),
         "value": "0x5"},
        {"from": "0x" + wallet.address.hex(),
         "to": "0x" + fleet_env["logger"].hex(), "data": "0x"},
        {"from": "0x" + wallet.address.hex(),
         "to": "0x" + fleet_env["kv"].hex(),
         "data": "0x" + (7).to_bytes(32, "big").hex()
                 + (9).to_bytes(32, "big").hex()},
    ]
    for call in calls:
        a = _rpc(http, "eth_call", [call, "latest"])
        b = _rpc(rport, "eth_call", [call, "latest"])
        assert a["result"] == b["result"], call
        a = _rpc(http, "eth_estimateGas", [call, "latest"])
        b = _rpc(rport, "eth_estimateGas", [call, "latest"])
        assert a["result"] == b["result"], call


def test_replica_logs_bit_identical(fleet_env):
    http, rport, tip = (fleet_env[k] for k in ("http", "rport", "tip"))
    filt = {"fromBlock": "0x1", "toBlock": hex(tip)}
    a = _rpc(http, "eth_getLogs", [filt])
    b = _rpc(rport, "eth_getLogs", [filt])
    assert a["result"] == b["result"]
    assert a["result"], "the logger call must actually emit a log"
    addr_filt = {**filt, "address": "0x" + fleet_env["logger"].hex()}
    assert (_rpc(http, "eth_getLogs", [addr_filt])["result"]
            == _rpc(rport, "eth_getLogs", [addr_filt])["result"])


def test_replica_proofs_bit_identical(fleet_env):
    http, rport = fleet_env["http"], fleet_env["rport"]
    wallet, kv = fleet_env["wallet"], fleet_env["kv"]
    for addr, slots in (("0x" + wallet.address.hex(), []),
                        ("0x" + kv.hex(), ["0x1", "0x3"]),
                        ("0x" + kv.hex(), ["0x2"])):  # deleted slot
        a = _rpc(http, "eth_getProof", [addr, slots, "latest"])
        b = _rpc(rport, "eth_getProof", [addr, slots, "latest"])
        assert a["result"] == b["result"], (addr, slots)


def test_replica_refuses_out_of_window_with_32001(fleet_env):
    rport, tip = fleet_env["rport"], fleet_env["tip"]
    # a hash the replica never saw
    resp = _rpc(rport, "eth_getBlockByHash", ["0x" + "ab" * 32, False])
    assert resp["error"]["code"] == -32001
    # logs from "earliest" reach below the replica window (no genesis)
    resp = _rpc(rport, "eth_getLogs", [{"fromBlock": "0x0",
                                        "toBlock": hex(tip)}])
    assert resp["error"]["code"] == -32001


def test_gateway_routes_reads_and_serves_fleet_admin(fleet_env):
    node, http = fleet_env["node"], fleet_env["http"]
    router = node.fleet_router
    node.gateway.on_head_change()  # drop cached entries: force routing
    before = router.snapshot()["routed"]
    wallet, sink = fleet_env["wallet"], fleet_env["sink"]
    for i in range(4):
        resp = _rpc(http, "eth_call",
                    [{"from": "0x" + wallet.address.hex(),
                      "to": "0x" + sink.hex(), "value": hex(0x40 + i)},
                     "latest"])
        assert "result" in resp, resp
    assert router.snapshot()["routed"] >= before + 4
    st = _rpc(http, "fleet_status", [])["result"]
    assert st["registered"] >= 1 and st["feed"]["subscribers"] >= 1
    # fleet admin rides the engine admission class (satellite contract)
    from reth_tpu.rpc.gateway import classify

    assert classify("fleet_status") == "engine"


def test_late_joiner_blinded_read_fails_over_bit_identical(fleet_env):
    """A replica joining after the feed backlog rotated holds only the
    newest blocks: a read through an unrevealed path answers -32001,
    and the SAME read through the fleet gateway still answers
    bit-identically via the local-fallback rung."""
    node, http = fleet_env["node"], fleet_env["http"]
    wallet, kv = fleet_env["wallet"], fleet_env["kv"]
    node.feed_server.backlog_cap = 1
    with node.feed_server._lock:
        del node.feed_server._backlog[:-1]
    late = ReplicaNode("127.0.0.1", fleet_env["fport"],
                       registry=MetricsRegistry(), replica_id="late")
    lport = late.start()
    router = node.fleet_router
    try:
        assert late.wait_synced(fleet_env["tip"], timeout=30)
        assert late.blocks_validated == 1  # only the backlog tail
        # slot 1 was written before the late joiner's window: its leaf
        # sits behind an unrevealed sibling hash -> clean -32001
        resp = _rpc(lport, "eth_getProof",
                    ["0x" + kv.hex(), ["0x1"], "latest"])
        assert resp["error"]["code"] == -32001
        assert late.blinded_reads >= 1
        # the same read through the gateway with ONLY the late replica
        # registered: replica -32001 -> failover -> local full node
        old = [h.id for h in router.replicas.values()]
        for rid in old:
            router.deregister(rid)
        router.register(f"http://127.0.0.1:{lport}")
        node.gateway.on_head_change()
        via_fleet = _rpc(http, "eth_getProof",
                         ["0x" + kv.hex(), ["0x1"], "latest"])
        assert "result" in via_fleet
        naked = RpcServer(lock=node.rpc.lock)
        naked.methods = node.rpc.methods
        expect = json.loads(naked.handle(json.dumps(
            {"jsonrpc": "2.0", "id": 1, "method": "eth_getProof",
             "params": ["0x" + kv.hex(), ["0x1"], "latest"]}).encode()))
        assert via_fleet["result"] == expect["result"]
        assert router.snapshot()["failovers"] >= 1
    finally:
        for h in list(router.replicas.values()):
            router.deregister(h.id)
        router.register(f"http://127.0.0.1:{fleet_env['rport']}")
        late.stop()


def test_wedged_replica_reports_and_sheds(fleet_env):
    node = fleet_env["node"]
    wedged = ReplicaNode(
        "127.0.0.1", fleet_env["fport"], registry=MetricsRegistry(),
        replica_id="wedged",
        injector=ReplicaFaultInjector(wedge=True))
    wport = wedged.start()
    router = node.fleet_router
    try:
        deadline = time.time() + 20
        while time.time() < deadline:
            if wedged.client.connected.is_set():
                break
            time.sleep(0.05)
        st = _rpc(wport, "fleet_status", [])["result"]
        assert st["wedged"] is True
        assert st["blocks_validated"] == 0  # every record dropped
        rid = router.register(f"http://127.0.0.1:{wport}")
        router.probe_once()
        snap = router.snapshot()
        mine = [r for r in snap["replicas"] if r["id"] == rid]
        assert mine and mine[0]["state"] == "draining"
    finally:
        router.deregister("wedged")
        for h in list(router.replicas.values()):
            if h.url.endswith(str(wport)):
                router.deregister(h.id)
        wedged.stop()


def test_events_line_carries_fleet_fragment(fleet_env):
    node = fleet_env["node"]
    node.event_reporter.on_canon_change([node.tree.blocks[h] for h in
                                         [node.tree.head_hash]])
    line = node.event_reporter.report_once()
    assert line is not None and "fleet[" in line and "feed=" in line


def test_health_rule_sees_fleet_component(fleet_env):
    from reth_tpu.health import HealthEngine

    eng = HealthEngine(interval=0)
    eng.tick()
    comps = eng.components()
    assert "fleet" in comps
    # a shed replica degrades the fleet component within one window
    node = fleet_env["node"]
    node.fleet_router.drain(next(iter(node.fleet_router.replicas)))
    eng.tick()
    assert eng.components()["fleet"] == "degraded"
    # restore for other tests
    for h in node.fleet_router.replicas.values():
        h.good_probes = 99
    node.fleet_router.probe_once()


# -- chaos drills (multi-process) ---------------------------------------------


@pytest.mark.slow
def test_fleet_chaos_sigkill_scenario(tmp_path):
    """SIGKILL one replica mid-load: zero failed reads, bit-identical
    responses, ring converges (chaos.py --domain fleet)."""
    from reth_tpu.chaos import make_fleet_scenario, run_fleet_scenario

    scn = make_fleet_scenario(3)
    assert scn["mode"] == "sigkill"
    res = run_fleet_scenario(scn, tmp_path, timeout=420)
    assert res.get("ok"), res


@pytest.mark.slow
def test_fleet_chaos_campaign_ten_seeds(tmp_path):
    """The acceptance matrix: 10 seeded fleet scenarios (sigkill/wedge/
    lag mixes composed with full-node injectors) all pass."""
    from reth_tpu.chaos import run_campaign

    results = run_campaign(range(1, 11), tmp_path, domain="fleet")
    bad = [r for r in results if not r.get("ok")]
    assert not bad, bad


@pytest.mark.slow
def test_fleet_bench_mode_e2e(tmp_path):
    """RETH_TPU_BENCH_MODE=fleet lands a verified number: responses
    checked bit-identical before measuring, per_fleet carries the
    1/2-replica curve."""
    import os
    import subprocess
    import sys
    from pathlib import Path

    env = {k: v for k, v in os.environ.items()
           if not k.startswith("RETH_TPU_FAULT_")}
    env.update(JAX_PLATFORMS="cpu", RETH_TPU_BENCH_MODE="fleet",
               RETH_TPU_BENCH_FLEET_SIZES="1,2",
               RETH_TPU_BENCH_FLEET_CLIENTS="3",
               RETH_TPU_BENCH_FLEET_REQS="15",
               RETH_TPU_BENCH_BASELINE_STORE=str(tmp_path / "bl.json"),
               RETH_TPU_BENCH_TIMEOUT="420")
    repo = Path(__file__).resolve().parent.parent
    r = subprocess.run([sys.executable, str(repo / "bench.py")],
                       capture_output=True, text=True, timeout=480,
                       env=env, cwd=repo)
    line = json.loads(r.stdout.strip().splitlines()[-1])
    assert line["metric"] == "fleet_requests_per_sec"
    assert line.get("error") is None
    assert line["value"] > 0
    assert set(line["per_fleet"]) == {"1", "2"}
    assert line["single_node"]["tail_rps"] > 0
    assert "bit-identical" in line["verified"]
