"""End-to-end staged sync: genesis → import → pipeline → roots match.

This is the reference's `sync.yml` flow in miniature (sync a chain,
verify the tip state root, then unwind) — SURVEY.md §7.5's minimum
end-to-end slice.
"""

import numpy as np
import pytest

from reth_tpu.consensus import EthBeaconConsensus
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256, keccak256_batch_np
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.storage.genesis import GenesisMismatch, import_chain, init_genesis
from reth_tpu.stages import Pipeline, default_stages
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)

STORE_CODE = bytes.fromhex("5f355f5500")  # sstore(0, calldata[0])


def initcode_for(runtime: bytes) -> bytes:
    n = len(runtime)
    return bytes([0x60, n, 0x60, 0x0B, 0x5F, 0x39, 0x60, n, 0x5F, 0xF3]) + b"\x00" + runtime


@pytest.fixture(scope="module")
def chain():
    """A 6-block chain with transfers, a deploy, contract calls, deletions."""
    alice = Wallet(0xA11CE)
    bob = Wallet(0xB0B)
    builder = ChainBuilder(
        {alice.address: Account(balance=10**21), bob.address: Account(balance=10**20)},
        committer=CPU,
    )
    # block 1: simple transfers
    builder.build_block([
        alice.transfer(bob.address, 10**18),
        bob.transfer(alice.address, 5 * 10**17),
    ])
    # block 2: deploy the storage contract
    blk2 = builder.build_block([alice.deploy(initcode_for(STORE_CODE))])
    contract = [
        a for a, acc in builder.accounts.items()
        if acc.code_hash == keccak256(STORE_CODE)
    ][0]
    # block 3: write storage slots
    builder.build_block([
        alice.call(contract, (0xBEEF).to_bytes(32, "big")),
    ])
    # block 4: overwrite slot + more transfers
    builder.build_block([
        alice.call(contract, (0xCAFE).to_bytes(32, "big")),
        alice.transfer(b"\x99" * 20, 123),
    ])
    # block 5: zero the slot (deletion in the storage trie)
    builder.build_block([alice.call(contract, b"\x00" * 32)])
    # block 6: empty block
    builder.build_block([])
    return builder


def fresh_synced_factory(chain, target=None):
    factory = ProviderFactory(MemDb())
    init_genesis(factory, chain.genesis, dict(chain.accounts_at_genesis), committer=CPU)
    import_chain(factory, chain.blocks[1:], EthBeaconConsensus(CPU))
    pipeline = Pipeline(factory, default_stages(committer=CPU))
    pipeline.run(target if target is not None else chain.tip.number)
    return factory, pipeline


def test_full_sync_to_tip(chain):
    factory, pipeline = fresh_synced_factory(chain)
    p = factory.provider()
    tip = chain.tip.number
    assert p.stage_checkpoint("Finish") == tip
    # every executed block's state root was validated by MerkleStage; spot
    # check the tip header matches what the builder sealed
    assert p.header_by_number(tip).state_root == chain.tip.state_root
    # plain state matches the builder's world
    for addr, acc in chain.accounts.items():
        got = p.account(addr)
        assert got is not None and got.balance == acc.balance and got.nonce == acc.nonce
    for addr, slots in chain.storages.items():
        for slot, val in slots.items():
            assert p.storage(addr, slot) == val
    # receipts exist and cumulative gas matches headers
    for n in range(1, tip + 1):
        idx = p.block_body_indices(n)
        if idx.tx_count:
            last = p.receipt(idx.last_tx_num)
            assert last.cumulative_gas_used == p.header_by_number(n).gas_used


def test_incremental_second_sync(chain):
    """Sync to block 3, then extend to tip — exercises incremental merkle."""
    factory, pipeline = fresh_synced_factory(chain, target=3)
    assert factory.provider().stage_checkpoint("Finish") == 3
    pipeline.run(chain.tip.number)
    p = factory.provider()
    assert p.stage_checkpoint("Finish") == chain.tip.number
    assert p.header_by_number(chain.tip.number).state_root == chain.tip.state_root


def test_unwind(chain):
    factory, pipeline = fresh_synced_factory(chain)
    pipeline.unwind(3)
    p = factory.provider()
    for stage in ("Execution", "MerkleExecute", "Finish"):
        assert p.stage_checkpoint(stage) == 3
    # state at block 3: contract slot holds 0xBEEF
    contract = [
        a for a, acc in chain.accounts.items()
        if acc.code_hash == keccak256(STORE_CODE)
    ][0]
    assert p.storage(contract, b"\x00" * 32) == 0xBEEF
    # resync forward reaches the tip again
    pipeline.run(chain.tip.number)
    p = factory.provider()
    assert p.stage_checkpoint("Finish") == chain.tip.number
    assert p.storage(contract, b"\x00" * 32) == 0


def test_tx_lookup(chain):
    factory, _ = fresh_synced_factory(chain)
    p = factory.provider()
    tx = chain.blocks[1].transactions[0]
    from reth_tpu.storage.tables import Tables, from_be64

    raw = p.tx.get(Tables.TransactionHashNumbers.name, tx.hash)
    assert raw is not None and from_be64(raw) == 0


def test_genesis_mismatch_detected(chain):
    factory = ProviderFactory(MemDb())
    init_genesis(factory, chain.genesis, dict(chain.accounts_at_genesis), committer=CPU)
    from reth_tpu.primitives.types import Header

    other = Header(number=0, state_root=b"\x11" * 32)
    with pytest.raises(GenesisMismatch):
        init_genesis(factory, other, {}, committer=CPU)
