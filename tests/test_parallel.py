"""Multi-chip sharding tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

from reth_tpu.primitives.keccak import keccak256, pad_batch


def test_graft_entry_single():
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import __graft_entry__ as g
    import jax

    fn, args = g.entry()
    out = np.asarray(jax.jit(fn)(*args))
    # spot check one digest against the reference
    from reth_tpu.primitives.keccak import keccak256

    rng = np.random.default_rng(0)
    msg0 = rng.integers(0, 256, size=100, dtype=np.uint8).tobytes()
    assert out[0].tobytes() == keccak256(msg0)


@pytest.mark.slow
def test_dryrun_multichip_8(monkeypatch):
    """(make test-mesh: two subprocess jax inits put this past the tier-1
    budget; the driver runs the same path itself for MULTICHIP capture.)"""
    import __graft_entry__ as g

    # test-sized workload: the dryrun's env defaults (4000 accounts) are
    # the driver's MULTICHIP capture; here we only pin the plumbing — the
    # bench mesh mode's own root-parity assertion still runs in full
    monkeypatch.setenv("RETH_TPU_BENCH_MESH_ACCOUNTS", "400")
    monkeypatch.setenv("RETH_TPU_BENCH_MESH_SLOTS", "150")
    monkeypatch.setenv("RETH_TPU_BENCH_MESH_TIER", "128")
    g.dryrun_multichip(8)


def test_sharded_keccak_matches_reference():
    import jax

    from reth_tpu.parallel import HashMesh, sharded_keccak

    mesh = HashMesh(jax.devices()[:4])
    rng = np.random.default_rng(5)
    msgs = [rng.integers(0, 256, size=77, dtype=np.uint8).tobytes() for _ in range(64)]
    words = np.ascontiguousarray(pad_batch(msgs, 1)).view("<u4").reshape(64, 34)
    digests = np.asarray(sharded_keccak(mesh, words))
    assert [digests[i].tobytes() for i in range(64)] == [keccak256(m) for m in msgs]
