"""EIP-1186 proof tests: generation + independent verification."""

import numpy as np

from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256, keccak256_batch_np
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.trie import TrieCommitter
from reth_tpu.trie.incremental import full_state_root
from reth_tpu.trie.proof import (
    ProofCalculator,
    verify_account_proof,
    verify_storage_proof,
)

CPU = TrieCommitter(hasher=keccak256_batch_np)


def setup_state(n_accounts=50, with_storage=True):
    rng = np.random.default_rng(3)
    factory = ProviderFactory(MemDb())
    addresses = [bytes(rng.integers(0, 256, 20, dtype=np.uint8)) for _ in range(n_accounts)]
    storages = {}
    with factory.provider_rw() as p:
        for i, a in enumerate(addresses):
            p.put_hashed_account(keccak256(a), Account(nonce=i, balance=1000 + i))
        if with_storage:
            for a in addresses[:5]:
                slots = {
                    bytes(rng.integers(0, 256, 32, dtype=np.uint8)): int(rng.integers(1, 2**60))
                    for _ in range(6)
                }
                storages[a] = slots
                for s, v in slots.items():
                    p.put_hashed_storage(keccak256(a), keccak256(s), v)
        root = full_state_root(p, CPU)
    return factory, addresses, storages, root


def test_account_proof_existing():
    factory, addrs, storages, root = setup_state()
    with factory.provider() as p:
        calc = ProofCalculator(p, CPU)
        proof = calc.account_proof(addrs[7])
    assert proof.account is not None and proof.account.balance == 1007
    assert verify_account_proof(root, addrs[7], proof)
    # tampered proof fails
    proof.account = proof.account.with_(balance=1)
    assert not verify_account_proof(root, addrs[7], proof)


def test_account_proof_absent():
    factory, addrs, storages, root = setup_state()
    missing = b"\x77" * 20
    with factory.provider() as p:
        proof = ProofCalculator(p, CPU).account_proof(missing)
    assert proof.account is None
    assert verify_account_proof(root, missing, proof)


def test_storage_proofs():
    factory, addrs, storages, root = setup_state()
    target = addrs[0]
    slots = list(storages[target].keys())[:3] + [b"\x55" * 32]  # 3 present + 1 absent
    with factory.provider() as p:
        proof = ProofCalculator(p, CPU).account_proof(target, slots)
    assert verify_account_proof(root, target, proof)
    assert len(proof.storage_proofs) == 4
    for sp in proof.storage_proofs[:3]:
        assert sp.value == storages[target][sp.key]
        assert verify_storage_proof(proof.storage_root, sp)
    absent = proof.storage_proofs[3]
    assert absent.value == 0
    assert verify_storage_proof(proof.storage_root, absent)


def test_multiproof_batched():
    """config #5 shape: many accounts in one batched proof computation."""
    factory, addrs, storages, root = setup_state(n_accounts=200)
    targets = {a: [] for a in addrs[:50]}
    with factory.provider() as p:
        proofs = ProofCalculator(p, CPU).multiproof(targets)
    assert len(proofs) == 50
    for a, proof in proofs.items():
        assert verify_account_proof(root, a, proof), a.hex()


def test_proof_empty_state():
    factory = ProviderFactory(MemDb())
    with factory.provider_rw() as p:
        root = full_state_root(p, CPU)
    with factory.provider() as p:
        proof = ProofCalculator(p, CPU).account_proof(b"\x01" * 20)
    assert proof.account is None
    assert verify_account_proof(root, b"\x01" * 20, proof)
