"""Peer reputation/banlist + invalid-block witness hooks."""

from __future__ import annotations

import json

from reth_tpu.engine import EngineTree
from reth_tpu.engine.invalid_hooks import InvalidBlockWitnessHook
from reth_tpu.net.reputation import BANNED_REPUTATION, PeersManager
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.primitives.types import Block, Header
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.storage.genesis import init_genesis
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)


def test_reputation_penalties_and_ban():
    pm = PeersManager(ban_seconds=9999)
    nid = b"\x01" * 64
    assert not pm.is_banned(nid)
    for _ in range(3):
        pm.reputation_change(nid, "bad_block")
    assert pm.reputation(nid) <= BANNED_REPUTATION
    assert pm.is_banned(nid)
    pm.unban(nid)
    assert not pm.is_banned(nid)
    assert pm.reputation(nid) == 0


def test_ban_expires():
    pm = PeersManager(ban_seconds=0.0)  # instant expiry
    nid = b"\x02" * 64
    pm.ban(nid)
    assert not pm.is_banned(nid)  # already served
    assert pm.reputation(nid) == 0


def test_good_behavior_offsets_penalties():
    pm = PeersManager()
    nid = b"\x03" * 64
    pm.reputation_change(nid, "timeout")
    pm.reputation_change(nid, "good")
    assert pm.reputation(nid) > -4_00


def test_invalid_block_hook_writes_witness(tmp_path):
    alice = Wallet(0xA11CE)
    bld = ChainBuilder({alice.address: Account(balance=10**21)}, committer=CPU)
    good = bld.build_block([alice.transfer(b"\x22" * 20, 5)])
    factory = ProviderFactory(MemDb())
    init_genesis(factory, bld.genesis, bld.accounts_at_genesis, committer=CPU)
    hook = InvalidBlockWitnessHook(tmp_path / "invalid")
    tree = EngineTree(factory, committer=CPU, invalid_block_hooks=[hook])
    # corrupt the state root: executes fine, roots diverge
    bad_header = Header(**{**good.header.__dict__, "state_root": b"\x66" * 32})
    bad = Block(bad_header, good.transactions, (), good.withdrawals)
    status = tree.on_new_payload(bad)
    assert status.status.name == "INVALID"
    files = list((tmp_path / "invalid").glob("*.json"))
    assert len(files) == 1
    witness = json.loads(files[0].read_text())
    assert witness["blockHash"] == "0x" + bad.hash.hex()
    assert "state root mismatch" in witness["reason"]
    assert witness["computedStateRoot"] != witness["headerStateRoot"]
    assert witness["blockRlp"].startswith("0x")
    assert witness["postAccounts"], "expected the execution delta"
