"""Replay the reference's hive rpc-compat chain as external ground truth.

The reference ships a 45-block test chain spanning EVERY fork
(homestead@0 ... tangerine@3 ... byzantium@9 ... london@27, the merge at
block 36, then shanghai/cancun/prague by timestamp) plus recorded
JSON-RPC request/response fixtures
(/root/reference/crates/rpc/rpc-e2e-tests/testdata/rpc-compat/). Importing
it through the real pipeline validates the fork-parameterized EVM against
externally produced headers: per-block gas used, receipts roots
(post-Byzantium), logs blooms, and the state root at every Merkle
checkpoint — the first full-chain validation of EVM + trie + RPC together
against data this repo did not generate.

The chain exercises: PoW headers + ommers (rewards!), pre-Byzantium
receipt format, EIP-1283/2200 SSTORE eras, the EIP-1559 activation
gas-limit doubling, the merge, withdrawals, blob fields, the EIP-4788 /
EIP-2935 system calls, and EIP-7702 set-code txs.
"""

from __future__ import annotations

import json
import urllib.request
from pathlib import Path

import pytest

from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.primitives.rlp import _decode_at
from reth_tpu.primitives.types import Block
from reth_tpu.trie import TrieCommitter

HIVE = Path("/root/reference/crates/rpc/rpc-e2e-tests/testdata/rpc-compat")

pytestmark = pytest.mark.skipif(
    not HIVE.exists(), reason="reference rpc-compat testdata not available")

CPU = TrieCommitter(hasher=keccak256_batch_np)


def _load_blocks() -> list[Block]:
    raw = (HIVE / "chain.rlp").read_bytes()
    blocks, pos = [], 0
    while pos < len(raw):
        _item, end = _decode_at(raw, pos)
        blocks.append(Block.decode(raw[pos:end]))
        pos = end
    return blocks


@pytest.fixture(scope="module")
def hive_node():
    from reth_tpu.cli import _load_genesis
    from reth_tpu.consensus import EthBeaconConsensus
    from reth_tpu.evm import EvmConfig
    from reth_tpu.node import Node, NodeConfig
    from reth_tpu.stages import Pipeline, default_stages
    from reth_tpu.storage.genesis import import_chain

    header, alloc, storage, codes, chain_id, chain_spec = _load_genesis(
        str(HIVE / "genesis.json"), CPU)
    cfg = NodeConfig(chain_id=chain_id, genesis_header=header,
                     genesis_alloc=alloc, genesis_storage=storage,
                     genesis_codes=codes, chain_spec=chain_spec,
                     db_backend="memdb")
    node = Node(cfg, committer=CPU)
    blocks = _load_blocks()
    consensus = EthBeaconConsensus(CPU, chainspec=chain_spec)
    tip = import_chain(node.factory, blocks, consensus)
    pipeline = Pipeline(node.factory, default_stages(
        committer=CPU, consensus=consensus,
        evm_config=EvmConfig(chain_id=chain_id, chainspec=chain_spec)))
    pipeline.run(tip)
    node.start_rpc()
    yield node, blocks
    node.stop()


def test_chain_imports_to_expected_head(hive_node):
    node, blocks = hive_node
    head_fcu = json.loads((HIVE / "headfcu.json").read_text())
    want_head = bytes.fromhex(
        head_fcu["params"][0]["headBlockHash"].removeprefix("0x"))
    assert blocks[-1].header.number == 45
    with node.factory.provider() as p:
        assert p.last_block_number() == 45
        assert p.canonical_hash(45) == want_head
        # MerkleStage already validated the state root against header 45;
        # assert the stored trie agrees with the header once more here
        assert p.header_by_number(45).state_root == blocks[-1].header.state_root


def _raw_rpc(port: int, payload: dict) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/", json.dumps(payload).encode(),
        {"Content-Type": "application/json"})
    return json.loads(urllib.request.urlopen(req, timeout=30).read())


def _io_cases():
    return sorted(HIVE.glob("*/*.io"))


@pytest.mark.parametrize("io_path", _io_cases(), ids=lambda p: p.parent.name + "/" + p.stem)
def test_io_fixture_replays_byte_compatible(hive_node, io_path):
    """Each recorded hive exchange must reproduce exactly: same result
    payload for the same request (modulo JSON key order)."""
    node, _ = hive_node
    port = node.rpc.port
    request = None
    for line in io_path.read_text().splitlines():
        line = line.strip()
        if line.startswith(">> "):
            request = json.loads(line[3:])
        elif line.startswith("<< "):
            assert request is not None, "response before request in fixture"
            expected = json.loads(line[3:])
            got = _raw_rpc(port, request)
            assert got.get("result") == expected.get("result"), (
                f"{io_path.name}: {json.dumps(got.get('result'), indent=1)}\n"
                f"!= expected {json.dumps(expected.get('result'), indent=1)}")
            assert ("error" in got) == ("error" in expected)
            request = None


def test_pre_byzantium_receipt_roots_match_headers():
    """Pre-Byzantium receipts embed the post-transaction STATE ROOT
    (EIP-658 replaced it with the status flag). The pipeline skips this
    check like the reference does, but the executor's
    ``intermediate_root_fn`` seam makes it checkable: replay the hive
    chain's pre-Byzantium segment (blocks 1-8) computing a full trie root
    after every tx, and the receipts roots must equal the externally
    produced headers'."""
    from reth_tpu.cli import _load_genesis
    from reth_tpu.consensus.validation import validate_block_post_execution
    from reth_tpu.evm import BlockExecutor, EvmConfig
    from reth_tpu.evm.executor import InMemoryStateSource
    from reth_tpu.trie import state_root
    from reth_tpu.trie.state_root import ordered_trie_root

    header, alloc, storage, codes, chain_id, chain_spec = _load_genesis(
        str(HIVE / "genesis.json"), CPU)
    blocks = _load_blocks()
    src = InMemoryStateSource(alloc, storage, codes)
    cfg = EvmConfig(chain_id=chain_id, chainspec=chain_spec)
    hashes = {0: header.hash}

    def root_fn(state):
        accounts = dict(src.accounts)
        storages = {a: dict(s) for a, s in src.storages.items()}
        for addr, acc in state._accounts.items():
            if acc is None:
                accounts.pop(addr, None)
            else:
                accounts[addr] = acc
        for addr in state._selfdestructs | state.changes.wiped_storage:
            storages.pop(addr, None)
        for addr, per in state._storage.items():
            tgt = storages.setdefault(addr, {})
            for slot, v in per.items():
                if v:
                    tgt[slot] = v
                else:
                    tgt.pop(slot, None)
            if not tgt:
                storages.pop(addr, None)
        # pre-Spurious tries CARRY empty accounts (EIP-161 is what removes
        # them); a full rebuild from plain state must include them
        root, _ = state_root(accounts, storages, committer=CPU,
                             include_empty=True)
        return root

    checked = 0
    for b in blocks[:8]:  # byzantium activates at block 9
        out = BlockExecutor(src, cfg).execute(
            b, block_hashes=dict(hashes), intermediate_root_fn=root_fn)
        hashes[b.header.number] = b.hash
        assert all(r.state_root is not None for r in out.receipts)
        got = ordered_trie_root([r.encode_2718() for r in out.receipts], CPU)
        assert got == b.header.receipts_root, f"block {b.header.number}"
        # the fork-aware post-exec validator must also accept it whole
        validate_block_post_execution(b, out.receipts, out.gas_used, CPU,
                                      chainspec=chain_spec)
        checked += len(out.receipts)
        for addr, acc in out.post_accounts.items():
            if acc is None:
                src.accounts.pop(addr, None)
            else:
                src.accounts[addr] = acc
        for addr in out.changes.wiped_storage:
            src.storages[addr] = {}
        for addr, slots in out.post_storage.items():
            per = src.storages.setdefault(addr, {})
            for slot, v in slots.items():
                if v:
                    per[slot] = v
                else:
                    per.pop(slot, None)
        for ch, code in out.changes.new_bytecodes.items():
            src.codes[ch] = code
    assert checked >= 20  # the segment is transaction-dense


def test_debug_trace_historical_block_uses_its_fork(hive_node):
    """debug_traceBlockByNumber re-executes under the block's OWN rule
    set (round-5: the trace paths take the node's chainspec-carrying
    EvmConfig). Block 5 is homestead/tangerine-era: tracing it under
    latest rules would reject its pre-EIP-155 transactions outright."""
    node, blocks = hive_node
    port = node.rpc.port
    got = _raw_rpc(port, {"jsonrpc": "2.0", "id": 1,
                          "method": "debug_traceBlockByNumber",
                          "params": ["0x5", {"tracer": "callTracer"}]})
    assert "error" not in got, got
    traces = got["result"]
    assert len(traces) == len(blocks[4].transactions)
    assert all("result" in t for t in traces)
