"""Fleet observability plane (ISSUE 14): cross-process trace
propagation (wire form, feed-frame + routed-RPC adoption, Chrome-trace
stitching), metrics federation (delta protocol, bucket-exact histogram
merge, scope=fleet, staleness degradation, fleet SLO rules), correlated
flight recorders (feed fan-out, merged time-aligned view), and the
overhead guards.

The @slow half runs the chaos ``--domain fleet`` wedge drill end to
end: full node + 2 replica subprocesses, one wedged mid-load — one
stitched trace spanning 3 pids with every cross-process parent id
resolving, ``/metrics?scope=fleet`` bucket-exact, and flight dumps from
all three processes under one correlation id."""

import json
import os
import pickle
import time
import urllib.request

import pytest

from reth_tpu import tracing
from reth_tpu.chaos import _fleet_metrics_bucket_exact
from reth_tpu.fleet.replica import ReplicaFaultInjector, ReplicaNode
from reth_tpu.metrics import MetricsRegistry, histogram_quantile
from reth_tpu.obs.federation import (
    FederationSource,
    MetricsFederation,
    get_federation,
)
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter


# -- wire form ----------------------------------------------------------------


def test_wire_form_roundtrip_and_garbage():
    tracing.set_trace_enabled(True)
    try:
        with tracing.trace_block("d7" * 32, number=3):
            with tracing.span("t", "x") as ctx:
                w = tracing.context_to_wire(ctx)
                assert w["t"] == "d7" * 32
                assert w["s"] == ctx.span_id
                assert w["p"] == os.getpid()
                assert isinstance(w["r"], str) and w["r"]
                back = tracing.context_from_wire(w)
                assert back.trace_id == ctx.trace_id
                assert back.span_id == ctx.span_id
    finally:
        tracing.set_trace_enabled(False)
    # span-only context (a routed read has no block trace id): still
    # encodes, still adoptable — stitching is by parent span id
    w = tracing.context_to_wire(tracing.TraceContext(None, 12345))
    assert w["t"] is None and w["s"] == 12345
    back = tracing.context_from_wire(w)
    assert back.trace_id is None and back.span_id == 12345
    # garbage never raises, never adopts
    for bad in (None, "x", 7, {}, {"t": 5}, {"t": "", "s": 1},
                {"t": None, "s": "nope"}, {"t": None, "s": None}):
        assert tracing.context_from_wire(bad) is None, bad
    # no context -> no bytes on the wire
    assert tracing.context_to_wire(None) is None


def test_span_ids_embed_pid_bits():
    tracing.set_trace_enabled(True)
    try:
        with tracing.span("t", "a") as c1:
            pass
        with tracing.span("t", "b") as c2:
            pass
    finally:
        tracing.set_trace_enabled(False)
    assert c1.span_id != c2.span_id
    mine = os.getpid() & 0x3FFFFF
    assert tracing.span_id_pid_bits(c1.span_id) == mine
    assert tracing.span_id_pid_bits(c2.span_id) == mine


def test_rpc_server_adopts_traceparent():
    """A request carrying a wire-form traceparent member executes under
    the remote context: handler-side spans stitch under the caller's."""
    from reth_tpu.rpc.server import RpcServer

    seen = {}

    class Api:
        def test_probe(self):
            seen["ctx"] = tracing.current_context()
            return "ok"

    srv = RpcServer()
    srv.register(Api())
    tracing.set_trace_enabled(True)
    rec = tracing.flight_recorder()
    before = rec.recorded
    try:
        body = json.dumps({
            "jsonrpc": "2.0", "id": 1, "method": "test_probe",
            "params": [],
            "traceparent": {"t": "ee" * 32, "s": 777, "r": "full",
                            "p": 42}}).encode()
        resp = json.loads(srv.handle(body))
        assert resp["result"] == "ok"
        # the handler ran under a span whose trace is the remote one
        assert seen["ctx"] is not None
        assert seen["ctx"].trace_id == "ee" * 32
        serve = [r for r in rec.snapshot(rec.recorded - before)
                 if r.get("name") == "rpc.serve"]
        assert serve and serve[-1]["trace"] == "ee" * 32
        assert serve[-1]["parent"] == 777  # the REMOTE span id
        # without a traceparent: no adoption, no rpc.serve span
        seen.clear()
        json.loads(srv.handle(json.dumps({
            "jsonrpc": "2.0", "id": 1, "method": "test_probe",
            "params": []}).encode()))
        assert seen["ctx"] is None or seen["ctx"].trace_id != "ee" * 32
    finally:
        tracing.set_trace_enabled(False)


def test_stitch_chrome_traces_cross_process(tmp_path):
    """Stitch logic on synthetic two-process traces: resolved
    cross-process parents stitch; a dangling cross-process parent is
    reported; same-process dangles don't fail the cross check."""
    pid_a, pid_b = 1000, 2000
    sid = lambda pid, n: ((pid & 0x3FFFFF) << 40) | n  # noqa: E731
    a = [{"name": "fleet.route", "ph": "X", "ts": 1.0, "dur": 5.0,
          "pid": pid_a, "tid": 1, "args": {"span_id": sid(pid_a, 1)}}]
    b = [{"name": "rpc.serve", "ph": "X", "ts": 2.0, "dur": 2.0,
          "pid": pid_b, "tid": 1,
          "args": {"span_id": sid(pid_b, 1),
                   "parent_id": sid(pid_a, 1)}},
         # same-process dangling parent (killed mid-request): tolerated
         {"name": "orphan", "ph": "X", "ts": 3.0, "dur": 1.0,
          "pid": pid_b, "tid": 1,
          "args": {"span_id": sid(pid_b, 9),
                   "parent_id": sid(pid_b, 8)}}]
    fa, fb = tmp_path / "a.json", tmp_path / "b.json"
    fa.write_text("[\n" + ",\n".join(json.dumps(e) for e in a) + "\n]\n")
    # torn tail: a killed process's half-written line is skipped
    fb.write_text("[\n" + ",\n".join(json.dumps(e) for e in b)
                  + ',\n{"name": "torn', )
    st = tracing.stitch_chrome_traces([fa, fb])
    assert st["pids"] == [pid_a, pid_b]
    assert st["cross_refs"] == 1
    assert st["unresolved_cross"] == []
    assert st["stitched"] is True
    # a dangling CROSS-process parent fails the stitch
    b2 = dict(b[0])
    b2["args"] = {"span_id": sid(pid_b, 2), "parent_id": sid(pid_a, 99)}
    fb.write_text("[\n" + json.dumps(b2) + "\n]\n")
    st = tracing.stitch_chrome_traces([fa, fb])
    assert st["unresolved_cross"] == [sid(pid_a, 99)]
    assert st["stitched"] is False
    # concatenation without any cross ref is NOT stitched
    st = tracing.stitch_chrome_traces([fa])
    assert st["stitched"] is False


def test_exporters_carry_process_identity(tmp_path):
    """OTLP spans carry role/pid/build resource attributes; the Chrome
    exporter emits per-process pid/tid metadata events (satellite)."""
    chrome = tmp_path / "c.json"
    otlp = tmp_path / "o.jsonl"
    tracing.init_block_tracing(chrome_path=chrome, otlp_path=otlp)
    try:
        with tracing.span("t", "probe"):
            pass
    finally:
        tracing.shutdown_block_tracing()
        tracing.set_trace_enabled(False)
    events = tracing.read_chrome_trace(chrome)
    meta = [e for e in events if e.get("ph") == "M"]
    names = {e["name"]: e for e in meta}
    assert "process_name" in names and "thread_name" in names
    assert names["process_name"]["pid"] == os.getpid()
    assert str(os.getpid()) in names["process_name"]["args"]["name"]
    line = json.loads(otlp.read_text().splitlines()[0])
    attrs = {a["key"]: a["value"]["stringValue"]
             for a in line["resource"]["attributes"]}
    assert attrs["process.pid"] == str(os.getpid())
    assert "service.role" in attrs
    assert "build.version" in attrs


# -- federation protocol ------------------------------------------------------


def test_federation_source_delta_encoding():
    reg = MetricsRegistry()
    c = reg.counter("reqs_total")
    g = reg.gauge("depth")
    h = reg.histogram("lat", buckets=(0.1, 1.0))
    c.increment(3)
    g.set(2)
    h.record(0.05)
    src = FederationSource(reg)
    s1 = src.snapshot()
    assert s1["full"] is True
    assert s1["metrics"]["reqs_total"] == {"k": "c", "v": 3.0}
    assert s1["metrics"]["lat"]["b"] == [0.1, 1.0]
    # nothing changed: empty delta
    s2 = src.snapshot(s1["cursor"])
    assert s2["full"] is False and s2["metrics"] == {}
    # deltas carry both absolute and increment
    c.increment(2)
    h.record(0.5)
    s3 = src.snapshot(s2["cursor"])
    assert s3["metrics"]["reqs_total"]["v"] == 5.0
    assert s3["metrics"]["reqs_total"]["d"] == 2.0
    assert s3["metrics"]["lat"]["dc"] == [0, 1, 0]
    assert s3["metrics"]["lat"]["dn"] == 1
    # a stale cursor (restart on either side) re-anchors with full state
    s4 = src.snapshot("bogus:cursor")
    assert s4["full"] is True and "reqs_total" in s4["metrics"]
    # bounded payload: over max_metrics series truncate, counted
    many = MetricsRegistry()
    for i in range(30):
        many.counter(f"m{i:02d}_total").increment()
    small = FederationSource(many, max_metrics=10)
    s = small.snapshot()
    assert len(s["metrics"]) == 10 and s["truncated"] == 20


class _FakeRouter:
    """Router stand-in: replicas answer fleet_metricsSnapshot directly
    from in-process FederationSources (None = unreachable)."""

    def __init__(self, sources):
        import threading

        self._lock = threading.RLock()
        self.sources = sources

        class _H:
            def __init__(self, rid):
                self.id = rid
                self.url = rid

        self.replicas = {rid: _H(rid) for rid in sources}

    def _rpc(self, url, method, params, ctx=None):
        assert method == "fleet_metricsSnapshot"
        src = self.sources[url]
        if src is None:
            raise OSError("replica down")
        return src.snapshot(params[0])


def test_federation_histogram_merge_property():
    """Property: for randomized per-replica histogram populations, the
    federated merge is bucket-exact (element-wise sum) and the fleet
    quantile equals histogram_quantile over the summed ground truth."""
    import random

    buckets = (0.001, 0.01, 0.1, 1.0)
    for seed in range(5):
        rnd = random.Random(seed)
        truth = [0] * (len(buckets) + 1)
        total = 0.0
        sources = {}
        for r in range(rnd.randint(2, 4)):
            reg = MetricsRegistry()
            h = reg.histogram("svc_seconds", buckets=buckets)
            for _ in range(rnd.randint(5, 60)):
                v = rnd.choice((0.0005, 0.005, 0.05, 0.5, 5.0))
                h.record(v)
                total += v
                for i, b in enumerate(buckets):
                    if v <= b:
                        truth[i] += 1
                        break
                else:
                    truth[-1] += 1
            sources[f"r{r}"] = FederationSource(reg)
        fed = MetricsFederation(_FakeRouter(sources), interval=0)
        fed.pull_once()
        merged = fed.fleet_counts("svc_seconds")
        assert merged is not None
        b, counts, s, n = merged
        assert counts == truth, (seed, counts, truth)
        assert n == sum(truth)
        assert s == pytest.approx(total)
        for q in (0.5, 0.9, 0.99):
            assert fed.fleet_quantile("svc_seconds", q) \
                == histogram_quantile(buckets, truth, q)
        # windowed: the first pull is a baseline (no deltas yet) —
        # record more, pull again, the window sees only the new deltas
        fresh = [0] * (len(buckets) + 1)
        for rid, src in sources.items():
            h = src.registry._metrics["svc_seconds"]
            h.record(0.0005)
            fresh[0] += 1
        fed.pull_once()
        wq = fed.fleet_quantile("svc_seconds", 0.5, samples=1)
        assert wq == histogram_quantile(buckets, fresh, 0.5)


def test_federation_marks_stale_and_degrades_gracefully():
    ra = FederationSource(MetricsRegistry())
    router = _FakeRouter({"ra": ra, "rb": None})
    fed = MetricsFederation(router, interval=0)
    t0 = time.perf_counter()
    fed.pull_once()
    wall = time.perf_counter() - t0
    assert wall < 5.0  # an unreachable replica never blocks the pass
    snap = fed.snapshot()
    assert snap["replicas"] == 2 and snap["stale"] == 1
    summ = fed.summary()
    assert summ["per_replica"]["rb"]["stale"] is True
    assert summ["per_replica"]["rb"]["last_error"]
    assert summ["per_replica"]["ra"]["stale"] is False
    assert 'fleetobs_replica_stale{replica="rb"} 1' in fed.render()
    # recovery: the replica answers again -> fresh, full re-anchor
    router.sources["rb"] = FederationSource(MetricsRegistry())
    fed.pull_once()
    assert fed.snapshot()["stale"] == 0
    # a deregistered replica falls out of the federated view
    del router.replicas["rb"]
    del router.sources["rb"]
    fed.pull_once()
    assert fed.snapshot()["replicas"] == 1


def test_deferred_wedge_injector():
    inj = ReplicaFaultInjector(wedge=True, wedge_after=3)
    assert inj.wedging is False
    assert inj.on_block(1) is False
    assert inj.on_block(2) is False
    assert inj.wedging is True  # the next record wedges
    assert inj.on_block(3) is True
    assert inj.dropped == 1
    # env form: integer value defers, "1"/truthy wedges from birth
    inj = ReplicaFaultInjector.from_env(
        {"RETH_TPU_FAULT_REPLICA_WEDGE": "4"})
    assert inj.wedge and inj.wedge_after == 4 and not inj.wedging
    inj = ReplicaFaultInjector.from_env(
        {"RETH_TPU_FAULT_REPLICA_WEDGE": "1"})
    assert inj.wedging is True


# -- in-process fleet: adoption, scope=fleet, correlated dumps ----------------


@pytest.fixture(scope="module")
def obs_fleet(tmp_path_factory):
    """A traced dev fleet in ONE process: full node (fleet mode) + one
    in-process replica over the real feed socket, span recording on,
    flight dumps into a shared directory."""
    from reth_tpu.node import Node, NodeConfig

    flight_dir = tmp_path_factory.mktemp("flight")
    old_env = os.environ.get("RETH_TPU_FLIGHT_DIR")
    os.environ["RETH_TPU_FLIGHT_DIR"] = str(flight_dir)
    rec = tracing.flight_recorder()
    old_dir = rec.directory
    rec.directory = flight_dir
    tracing.set_trace_enabled(True)
    committer = TrieCommitter(hasher=keccak256_batch_np)
    committer.turbo_backend = "numpy"
    wallet = Wallet(0x0B5F1EE7)
    builder = ChainBuilder({wallet.address: Account(balance=10**21)},
                           committer=committer)
    node = Node(NodeConfig(dev=True, genesis_header=builder.genesis,
                           genesis_alloc=builder.accounts_at_genesis,
                           fleet=True, http_port=0, authrpc_port=0),
                committer=committer)
    node.fleet_router.probe_interval = 0      # probed explicitly
    node.fleet_federation.interval = 0        # pulled explicitly
    http, _ = node.start_rpc()
    replica_registry = MetricsRegistry()
    replica = ReplicaNode("127.0.0.1", node.feed_server.port,
                          registry=replica_registry,
                          replica_id="obs-replica")
    rport = replica.start()
    sink = b"\x0b" * 20
    for i in range(3):
        node.pool.add_transaction(wallet.transfer(sink, 100 + i))
        node.miner.mine_block(timestamp=1_700_000_000 + i * 12)
    assert replica.wait_synced(3, timeout=60), node.feed_server.snapshot()
    rid = node.fleet_router.register(f"http://127.0.0.1:{rport}")
    node.fleet_router.probe_once()
    env = {"node": node, "replica": replica, "wallet": wallet,
           "http": http, "rport": rport, "rid": rid, "sink": sink,
           "tip": 3, "replica_registry": replica_registry,
           "flight_dir": flight_dir}
    yield env
    replica.stop()
    node.stop()
    tracing.set_trace_enabled(False)
    rec.directory = old_dir
    if old_env is None:
        os.environ.pop("RETH_TPU_FLIGHT_DIR", None)
    else:
        os.environ["RETH_TPU_FLIGHT_DIR"] = old_env


def _rpc(port, method, params):
    body = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                       "params": params}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/", data=body,
        headers={"Content-Type": "application/json"})
    return json.loads(urllib.request.urlopen(req, timeout=15).read())


def test_feed_record_adopts_into_block_trace(obs_fleet):
    """A fed block's record carries the block trace's wire form, and
    the replica's stateless.validate span lands in the SAME trace with
    the witness.generate span as its parent."""
    node, wallet, sink = (obs_fleet[k] for k in ("node", "wallet", "sink"))
    rec = tracing.flight_recorder()
    node.pool.add_transaction(wallet.transfer(sink, 999))
    blk = node.miner.mine_block(timestamp=1_700_000_999)
    obs_fleet["replica"].wait_synced(blk.header.number, timeout=60)
    obs_fleet["tip"] = blk.header.number
    trace_id = blk.hash.hex()
    deadline = time.time() + 10
    wit = val = None
    while time.time() < deadline and (wit is None or val is None):
        records = rec.snapshot()
        wit = next((r for r in records
                    if r["name"] == "witness.generate"
                    and r["trace"] == trace_id), None)
        val = next((r for r in records
                    if r["name"] == "stateless.validate"
                    and r["trace"] == trace_id), None)
        time.sleep(0.05)
    assert wit is not None, "witness.generate span missing"
    assert val is not None, "replica validate span not in the block trace"
    assert val["parent"] == wit["span"], (val, wit)


def test_routed_read_stitches_and_attributes_replica(obs_fleet):
    """A fleet-routed read: the gateway's fleet.route span is tagged
    with the serving replica id, the replica-side rpc.serve span adopts
    it as parent (cross-process contract, here one process), and the
    per-replica labeled counter moves."""
    from reth_tpu.metrics import REGISTRY

    node, rid = obs_fleet["node"], obs_fleet["rid"]
    rec = tracing.flight_recorder()
    node.gateway.on_head_change()  # force routing (cache miss)
    before = node.fleet_router.snapshot()["routed"]
    resp = _rpc(obs_fleet["http"], "eth_call",
                [{"from": "0x" + obs_fleet["wallet"].address.hex(),
                  "to": "0x" + obs_fleet["sink"].hex(),
                  "value": hex(0xBEEF)}, "latest"])
    assert "result" in resp, resp
    assert node.fleet_router.snapshot()["routed"] == before + 1
    records = rec.snapshot()
    route = [r for r in records if r["name"] == "fleet.route"]
    assert route, "no fleet.route span recorded"
    assert route[-1]["fields"]["replica"] == rid
    serve = [r for r in records if r["name"] == "rpc.serve"
             and r["parent"] == route[-1]["span"]]
    assert serve, "replica rpc.serve span did not adopt fleet.route"
    # satellite: replica-id-labeled routing counters on /metrics
    text = REGISTRY.render()
    assert f'fleet_routed_total{{replica="{rid}"}}' in text


def test_metrics_scope_fleet_bucket_exact(obs_fleet):
    """GET /metrics?scope=fleet: per-replica-labeled series match the
    replica's own registry bucket-exactly; the _fleet merge is the
    bucket-wise sum (acceptance contract)."""
    node = obs_fleet["node"]
    node.fleet_federation.pull_once()
    fleet_text = urllib.request.urlopen(
        f"http://127.0.0.1:{obs_fleet['http']}/metrics?scope=fleet",
        timeout=10).read().decode()
    own_text = obs_fleet["replica_registry"].render()
    assert _fleet_metrics_bucket_exact(
        fleet_text, own_text, obs_fleet["rid"], "replica_validate_seconds")
    # without the scope param the federated series stay out (the
    # node's OWN per-replica routing counters still render — they
    # live in the local registry)
    plain = urllib.request.urlopen(
        f"http://127.0.0.1:{obs_fleet['http']}/metrics",
        timeout=10).read().decode()
    assert "replica_validate_seconds_bucket{replica=" not in plain
    assert 'replica="_fleet"' not in plain


def test_debug_fleet_metrics_rpc(obs_fleet):
    from reth_tpu.rpc.gateway import classify

    node = obs_fleet["node"]
    node.fleet_federation.pull_once()
    out = _rpc(obs_fleet["http"], "debug_fleetMetrics", [])["result"]
    assert out["replicas"] == 1 and out["stale"] == 0
    per = out["per_replica"][obs_fleet["rid"]]
    assert per["stale"] is False and per["series"] > 0
    assert "replica_validate_seconds" in out["fleet_quantiles"]
    assert out["fleet_quantiles"]["replica_validate_seconds"]["p99"] > 0
    # monitoring probe: rides the read class, never queues behind a
    # debug_traceBlock (same contract as debug_healthCheck)
    assert classify("debug_fleetMetrics") == "read"
    # classification satellite: the pull RPC rides the engine class
    assert classify("fleet_metricsSnapshot") == "engine"


def test_fleet_slo_rules(obs_fleet):
    """The new fleet rules evaluate against the installed federation:
    healthy fleet -> ok; a stale replica degrades the fleet component
    within one window."""
    from reth_tpu.health import HealthEngine

    node = obs_fleet["node"]
    assert get_federation() is node.fleet_federation
    node.fleet_federation.pull_once()
    eng = HealthEngine(interval=0)
    eng.tick()
    by_name = {r["rule"]: r for r in eng.slo_status()["rules"]}
    for rule in ("fleet_read_p99", "fleet_replica_lag",
                 "fleet_federation_stale"):
        assert rule in by_name, rule
        assert by_name[rule]["state"] == "ok", by_name[rule]
    # lag rule actually read the federated gauge (0 on a synced fleet)
    assert by_name["fleet_replica_lag"]["value"] == 0
    # an unreachable replica makes the federation stale -> degraded
    dead = node.fleet_router.register("http://127.0.0.1:9", rid="dead")
    try:
        node.fleet_federation.pull_once()
        eng.tick()
        st = {r["rule"]: r["state"] for r in eng.slo_status()["rules"]}
        assert st["fleet_federation_stale"] == "degraded"
        assert eng.components()["fleet"] == "degraded"
    finally:
        node.fleet_router.deregister(dead)
        node.fleet_federation.pull_once()


def test_correlated_dump_fans_over_feed(obs_fleet):
    """A node-side fault event dumps locally AND fans the request over
    the feed; the replica dumps under the SAME correlation id; the
    merged view is time-ordered and served by debug_flightRecorder.

    The replica's own observer is detached for the test: in ONE
    process both coordinators hang off the same fault hook, so the
    replica would pre-mark the id before the fanned frame arrives —
    a dedupe that in real deployments only fires for dumps the replica
    itself initiated."""
    node, replica = obs_fleet["node"], obs_fleet["replica"]
    flight_dir = obs_fleet["flight_dir"]
    tracing.reset_fault_dump_limits()
    tracing.remove_fault_observer(replica._on_local_fault)
    before = node.feed_server.flight_fanouts
    try:
        path = tracing.fault_event("TEST_FLEET_OBS_DRILL", target="test",
                                   probe=1)
    finally:
        tracing.add_fault_observer(replica._on_local_fault)
    assert path is not None
    header, _ = tracing.load_flight_dump(path)
    cid = header["correlation_id"]
    assert cid and node.feed_server.flight_fanouts == before + 1
    deadline = time.time() + 15
    merged = {}
    while time.time() < deadline:
        merged = tracing.merge_correlated(cid, flight_dir)
        if len(merged["dumps"]) >= 2:
            break
        time.sleep(0.05)
    assert len(merged["dumps"]) >= 2, merged  # node + replica
    ts = [r["ts"] for r in merged["records"]]
    assert ts == sorted(ts)
    assert all("pid" in r and "role" in r for r in merged["records"])
    # the RPC surface returns the same merged view
    out = _rpc(obs_fleet["http"], "debug_flightRecorder",
               ["correlated", 64, cid])["result"]
    assert out["correlation_id"] == cid
    assert len(out["dumps"]) == len(merged["dumps"])
    assert out["records"]


def test_replica_fault_notifies_upstream(obs_fleet):
    """The replica half of the correlated-dump channel: a replica-side
    fault event sends the request UPSTREAM on the feed socket and the
    full node dumps under the same correlation id. (The node-side
    observer is detached: one process, see the fan-out test.)"""
    node, replica = obs_fleet["node"], obs_fleet["replica"]
    flight_dir = obs_fleet["flight_dir"]
    tracing.reset_fault_dump_limits()
    before = node.feed_server.flight_requests
    sent0 = replica.client.sent_upstream
    tracing.remove_fault_observer(node._fleet_fault_observer)
    try:
        path = tracing.fault_event("TEST_REPLICA_OBS_DRILL", target="test")
    finally:
        tracing.add_fault_observer(node._fleet_fault_observer)
    assert path is not None
    cid = tracing.load_flight_dump(path)[0]["correlation_id"]
    deadline = time.time() + 15
    merged = {}
    while time.time() < deadline:
        merged = tracing.merge_correlated(cid, flight_dir)
        if len(merged["dumps"]) >= 2:
            break
        time.sleep(0.05)
    assert replica.client.sent_upstream > sent0
    assert node.feed_server.flight_requests == before + 1
    assert len(merged["dumps"]) >= 2, merged  # replica initiator + node


def test_events_line_carries_fleetobs_fragment(obs_fleet):
    node = obs_fleet["node"]
    node.fleet_federation.pull_once()
    node.event_reporter.on_canon_change(
        [node.tree.blocks[node.tree.head_hash]])
    line = node.event_reporter.report_once()
    assert line is not None and "fleetobs[" in line, line
    assert "pulls=" in line


# -- overhead guards ----------------------------------------------------------


def _sparse_wall():
    import numpy as np

    from reth_tpu.trie.sparse import ParallelSparseCommitter, SparseStateTrie

    rng = np.random.default_rng(7)
    st = SparseStateTrie()
    for _ in range(24):
        ha = bytes(rng.integers(0, 256, 32, dtype=np.uint8))
        t = st.storage_trie(ha)
        for _ in range(24):
            t.update(bytes(rng.integers(0, 256, 32, dtype=np.uint8)),
                     bytes(rng.integers(1, 256, 8, dtype=np.uint8)))
        st.update_account(ha, b"leaf-" + ha)
    committer = ParallelSparseCommitter(workers=2)
    t0 = time.perf_counter()
    st.root(keccak256_batch_np, committer=committer)
    wall = time.perf_counter() - t0
    committer.shutdown()
    return wall


def test_wire_form_and_federation_overhead_guard():
    """Satellite: trace wire-form encode/decode and one steady-state
    federation snapshot each cost <1% of a sparse-commit wall — the
    fleet obs plane rides the hot path for (nearly) free."""
    from reth_tpu.metrics import REGISTRY

    wall = _sparse_wall()
    # wire form: one encode+decode per cross-process hop; budget 100
    # hops per block against 1% of the commit wall
    ctx = tracing.TraceContext("ab" * 32, 12345)
    reps = 20_000
    t0 = time.perf_counter()
    for _ in range(reps):
        tracing.context_from_wire(tracing.context_to_wire(ctx))
    per_op = (time.perf_counter() - t0) / reps
    assert 100 * per_op < 0.01 * wall, (
        f"wire form costs {per_op * 1e6:.2f}µs/op on a "
        f"{wall * 1e3:.1f}ms commit")
    # federation: one steady-state (delta, mostly-unchanged) snapshot
    # of the REAL process registry per interval
    src = FederationSource(REGISTRY)
    cur = src.snapshot()["cursor"]  # anchor
    t0 = time.perf_counter()
    for _ in range(20):
        cur = src.snapshot(cur)["cursor"]
    per_pull = (time.perf_counter() - t0) / 20
    assert per_pull < 0.01 * wall, (
        f"federation snapshot costs {per_pull * 1e3:.3f}ms on a "
        f"{wall * 1e3:.1f}ms commit")


def test_feed_frame_traceparent_byte_overhead():
    """Satellite: the wire-form member adds <1% to a realistic witness
    record's framed bytes."""
    # distinct per-entry contents: pickle memoizes identical constant
    # objects, which would shrink the record far below a real witness
    record = {
        "type": "block", "number": 7, "hash": bytes(range(32)),
        "parent": bytes(range(1, 33)), "block_rlp": os.urandom(2048),
        "senders": [os.urandom(20) for _ in range(8)],
        "witness": {"state": [os.urandom(100) for _ in range(192)],
                    "codes": [os.urandom(256) for _ in range(4)],
                    "keys": [os.urandom(32) for _ in range(32)],
                    "headers": [os.urandom(500)]},
    }
    bare = len(pickle.dumps(record, protocol=pickle.HIGHEST_PROTOCOL))
    record["tp"] = tracing.context_to_wire(
        tracing.TraceContext("ab" * 32, (os.getpid() << 40) | 12345))
    framed = len(pickle.dumps(record, protocol=pickle.HIGHEST_PROTOCOL))
    added = framed - bare
    assert added > 0
    assert added < 0.01 * bare, (
        f"traceparent adds {added}B to a {bare}B record")


# -- the acceptance drill (multi-process) -------------------------------------


@pytest.mark.slow
def test_chaos_fleet_wedge_drill_obs_acceptance(tmp_path):
    """The ISSUE-14 acceptance scenario: chaos --domain fleet with a
    replica wedged MID-load (full node + 2 replica subprocesses) —
    one stitched trace spanning >=3 pids with every cross-process
    parent id resolving, /metrics?scope=fleet bucket-exact vs the
    survivor's registry, and flight dumps from all three processes
    sharing one correlation id, merged time-ordered."""
    from reth_tpu.chaos import make_fleet_scenario, run_fleet_scenario

    scn = make_fleet_scenario(10)
    assert scn["mode"] == "wedge"
    res = run_fleet_scenario(scn, tmp_path, timeout=420)
    assert res.get("ok"), res
    inv = res["invariants"]
    for key in ("trace_stitched", "fleet_metrics",
                "fleet_metrics_degraded_visible", "correlated_dump",
                "correlated_time_ordered"):
        assert inv.get(key) is True, (key, res)
    assert len(res["trace"]["pids"]) >= 3
    assert res["trace"]["cross_refs"] > 0
    assert res["trace"]["unresolved_cross"] == []
    assert len(res["correlated"]["pids"]) >= 3
