"""Aux subsystems: pruner, ExEx WAL, metrics endpoint, TOML config."""

import urllib.request

from reth_tpu.config import load_config
from reth_tpu.exex import CanonStateNotification, ExExManager
from reth_tpu.metrics import MetricsRegistry
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.prune import PruneMode, PruneModes, Pruner
from reth_tpu.consensus import EthBeaconConsensus
from reth_tpu.stages import Pipeline, default_stages
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.storage.genesis import import_chain, init_genesis
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)


def synced_factory(n_blocks=6):
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)}, committer=CPU)
    for i in range(n_blocks):
        builder.build_block([alice.transfer(b"\x0b" * 20, 100 + i)])
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis, committer=CPU)
    import_chain(factory, builder.blocks[1:], EthBeaconConsensus(CPU))
    Pipeline(factory, default_stages(committer=CPU)).run(n_blocks)
    return factory, builder


def test_pruner_receipts_and_senders():
    factory, _ = synced_factory()
    modes = PruneModes(
        receipts=PruneMode(distance=2),
        sender_recovery=PruneMode(distance=2),
        transaction_lookup=PruneMode(before=3),
    )
    progress = Pruner(factory, modes).run(tip=6)
    assert {p.segment for p in progress} == {"SenderRecovery", "Receipts", "TransactionLookup"}
    p = factory.provider()
    # blocks 1..3 pruned (tip 6, distance 2 → target 3)
    assert p.receipt(0) is None and p.sender(0) is None
    # blocks 4..6 retained
    idx4 = p.block_body_indices(4)
    assert p.receipt(idx4.first_tx_num) is not None
    # lookup pruned only before block 3
    tx_b1 = p.transactions_by_block(1)[0]
    tx_b5 = p.transactions_by_block(5)[0]
    from reth_tpu.storage.tables import Tables

    assert p.tx.get(Tables.TransactionHashNumbers.name, tx_b1.hash) is None
    assert p.tx.get(Tables.TransactionHashNumbers.name, tx_b5.hash) is not None
    # second run is a no-op (checkpoints advanced)
    assert Pruner(factory, modes).run(tip=6) == []


def test_exex_wal_and_replay(tmp_path):
    mgr = ExExManager(tmp_path)
    seen = []
    mgr.register("indexer", lambda n: seen.append(n.tip_number))
    for i in range(1, 4):
        mgr.notify(CanonStateNotification(i, bytes([i]) * 32, [(i, bytes([i]) * 32)]))
    assert seen == [1, 2, 3]
    assert mgr.finished_height() == 3

    # restart: new manager replays the WAL above the ExEx's durable height
    mgr2 = ExExManager(tmp_path)
    seen2 = []
    mgr2.register("indexer", lambda n: seen2.append(n.tip_number))
    replayed = mgr2.replay(from_height=1)
    assert replayed == 2 and seen2 == [2, 3]
    # prune acknowledged records
    mgr2.prune_wal(below_height=2)
    mgr3 = ExExManager(tmp_path)
    got = []
    mgr3.register("x", lambda n: got.append(n.tip_number))
    mgr3.replay()
    assert got == [3]


def test_metrics_render():
    reg = MetricsRegistry()
    reg.counter("blocks_total", "blocks").increment(5)
    reg.gauge("head_number").set(42)
    h = reg.histogram("root_seconds", buckets=(0.1, 1.0))
    h.record(0.05)
    h.record(0.5)
    h.record(10)
    text = reg.render()
    assert "blocks_total 5.0" in text
    assert "head_number 42" in text
    assert 'root_seconds_bucket{le="0.1"} 1' in text
    assert 'root_seconds_bucket{le="1.0"} 2' in text
    assert 'root_seconds_bucket{le="+Inf"} 3' in text
    assert "root_seconds_count 3" in text


def test_metrics_http_endpoint():
    from reth_tpu.metrics import REGISTRY
    from reth_tpu.rpc import RpcServer

    REGISTRY.counter("test_http_metric").increment()
    srv = RpcServer()
    port = srv.start()
    try:
        body = urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=10).read()
        assert b"test_http_metric" in body
    finally:
        srv.stop()


def test_static_file_producer_and_fallback(tmp_path):
    factory, builder = synced_factory()
    producer = __import__(
        "reth_tpu.storage.static_files", fromlist=["StaticFileProducer"]
    ).StaticFileProducer(factory, tmp_path / "static")
    moved = producer.run(to_block=4)
    assert moved["headers"] == 5  # blocks 0..4
    assert moved["transactions"] == 4  # blocks 1..4, one tx each
    # DB rows for the moved range are gone...
    from reth_tpu.storage.tables import Tables, be64

    p = factory.provider()
    assert p.tx.get(Tables.Transactions.name, be64(0)) is None
    # ...but a static-file-aware factory still serves them
    factory2 = ProviderFactory(factory.db, producer.static)
    p2 = factory2.provider()
    txs = p2.transactions_by_block(1)
    assert len(txs) == 1 and txs[0].value == 100
    assert p2.receipt(0) is not None
    # incremental second run picks up where it left off
    moved2 = producer.run(to_block=6)
    assert moved2["headers"] == 2
    assert factory2.provider().transactions_by_block(6)[0].value == 105


def test_config_toml(tmp_path):
    cfg_file = tmp_path / "reth.toml"
    cfg_file.write_text("""
[stages.merkle]
rebuild_threshold = 123
incremental_threshold = 45

[prune.receipts]
distance = 100

[node]
persistence_threshold = 5
hasher = "cpu"
""")
    cfg = load_config(cfg_file)
    assert cfg.stages.merkle.rebuild_threshold == 123
    assert cfg.prune.receipts.distance == 100
    assert cfg.prune.sender_recovery.distance is None
    assert cfg.persistence_threshold == 5
    assert cfg.hasher == "cpu"
    # missing file → defaults
    assert load_config(tmp_path / "nope.toml").stages.merkle.rebuild_threshold == 50_000


def test_static_file_compression_tiers(tmp_path):
    """NippyJar-style per-column tiers: incompressible columns store raw,
    repetitive ones compress; old all-zlib files still read."""
    import json as _json
    import struct as _struct
    import zlib as _zlib

    from reth_tpu.storage.nippyjar import LEGACY_MAGIC as MAGIC
    from reth_tpu.storage.static_files import SegmentFile, write_segment_file

    import os
    hashes = [os.urandom(32) for _ in range(40)]          # incompressible
    blobs = [b"A" * 600 + bytes([i]) for i in range(40)]  # very repetitive
    path = tmp_path / "seg_0_39.sf"
    write_segment_file(path, "headers", 0, {"hash": hashes, "header": blobs})
    sf = SegmentFile.open(path)
    assert sf._jar._codecs["hash"] == "none"
    assert sf._jar._codecs["header"] in ("zlib", "lzma")
    for i in (0, 17, 39):
        assert sf.row(i, "hash") == hashes[i]
        assert sf.row(i, "header") == blobs[i]
    sf.close()

    # legacy format (no compression key, all zlib) still reads
    header = _json.dumps({"segment": "headers", "start": 0, "count": 2,
                          "columns": ["header"]}).encode()
    rows = [b"old-one", b"old-two"]
    with open(tmp_path / "legacy_0_1.sf", "wb") as f:
        f.write(MAGIC)
        f.write(_struct.pack("<I", len(header)))
        f.write(header)
        payload = [_zlib.compress(r) for r in rows]
        offs = [0]
        for b in payload:
            offs.append(offs[-1] + len(b))
        f.write(_struct.pack("<3Q", *offs))
        for b in payload:
            f.write(b)
    old = SegmentFile.open(tmp_path / "legacy_0_1.sf")
    assert old.row(0, "header") == b"old-one"
    assert old.row(1, "header") == b"old-two"
    old.close()


def test_trie_metrics_record_on_turbo_commit():
    import numpy as np

    from reth_tpu.metrics import trie_metrics
    from reth_tpu.primitives.rlp import rlp_encode
    from reth_tpu.trie.turbo import TurboCommitter

    rng = np.random.default_rng(1)
    keys = rng.integers(0, 256, (64, 32), dtype=np.uint8)
    vals = [rlp_encode(bytes([i])) for i in range(64)]
    before = trie_metrics._commits.value
    TurboCommitter(backend="numpy").commit_hashed_many([(keys, vals)])
    assert trie_metrics._commits.value == before + 1
    assert trie_metrics.last["backend"] == "numpy"
    assert trie_metrics.last["leaves"] == 64
    assert trie_metrics.last["nodes"] > 0
    assert trie_metrics.last["wire_bytes"] > 0
