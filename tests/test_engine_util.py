"""Engine fault-injection middleware + message store + debug CL client.

Reference analogue: crates/engine/util (EngineReorg/EngineSkip/
engine-store) and crates/consensus/debug-client.
"""

import pytest

from reth_tpu.consensus import EthBeaconConsensus
from reth_tpu.consensus.debug_client import DebugConsensusClient, RpcBlockSource
from reth_tpu.engine import EngineTree
from reth_tpu.engine.tree import PayloadStatusKind
from reth_tpu.engine.util import EngineFaultInjector, EngineMessageStore
from reth_tpu.node import Node, NodeConfig
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.storage.genesis import init_genesis
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)


def make_chain(n_blocks=6):
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)}, committer=CPU)
    for i in range(n_blocks):
        builder.build_block([alice.transfer(b"\x0b" * 20, 100 + i)])
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis, committer=CPU)
    return builder, factory


def test_skip_new_payload_and_fcu():
    builder, factory = make_chain(4)
    tree = EngineTree(factory, committer=CPU)
    inj = EngineFaultInjector(tree, skip_new_payload=2, skip_fcu=3)
    statuses = []
    for b in builder.blocks[1:]:
        st = inj.on_new_payload(b)
        statuses.append(st.status)
        inj.on_forkchoice_updated(b.hash)
    # every 2nd payload dropped as SYNCING, every 3rd FCU swallowed
    assert statuses[0] is PayloadStatusKind.VALID
    assert statuses[1] is PayloadStatusKind.SYNCING
    assert inj.skipped_payloads == 2
    assert inj.skipped_fcu == 1


def test_reorg_injection_exercises_tree_reorg_path():
    builder, factory = make_chain(5)
    tree = EngineTree(factory, committer=CPU)
    inj = EngineFaultInjector(tree, reorg_frequency=2)
    for b in builder.blocks[1:]:
        assert inj.on_new_payload(b).status is PayloadStatusKind.VALID
        inj.on_forkchoice_updated(b.hash)
    assert inj.injected_reorgs >= 1
    # the tree still lands on the right head
    assert tree.head_hash == builder.tip.hash


def test_message_store_records_and_replays(tmp_path):
    builder, factory = make_chain(3)
    tree = EngineTree(factory, committer=CPU)
    store = EngineMessageStore(tree, tmp_path / "engine.jsonl")
    for b in builder.blocks[1:]:
        store.on_new_payload(b)
        store.on_forkchoice_updated(b.hash)
    # replay the captured stream into a FRESH tree
    _, factory2 = make_chain(0)
    tree2 = EngineTree(factory2, committer=CPU)
    n = EngineMessageStore.replay(tmp_path / "engine.jsonl", tree2)
    assert n == 6
    assert tree2.head_hash == builder.tip.hash


def test_debug_client_follows_rpc_source():
    """One node mines; a second follows it through the debug CL client."""
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)}, committer=CPU)
    cfg = NodeConfig(dev=True, genesis_header=builder.genesis,
                     genesis_alloc=builder.accounts_at_genesis)
    source_node = Node(cfg, committer=CPU)
    source_node.start_rpc()
    try:
        from reth_tpu.rpc.convert import data

        from test_rpc_e2e import rpc

        for i in range(3):
            tx = alice.transfer(b"\x0b" * 20, 100 + i)
            rpc(source_node.rpc.port, "eth_sendRawTransaction", data(tx.encode()))
            source_node.miner.mine_block()

        follower_factory = ProviderFactory(MemDb())
        init_genesis(follower_factory, builder.genesis,
                     builder.accounts_at_genesis, committer=CPU)
        follower = EngineTree(follower_factory, committer=CPU)
        client = DebugConsensusClient(
            follower,
            RpcBlockSource(f"http://127.0.0.1:{source_node.rpc.port}/"))
        assert client.run_once() == 3
        assert client.run_once() == 0  # caught up, idempotent
        assert follower.head_hash == source_node.tree.head_hash
    finally:
        source_node.stop()
