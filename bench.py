"""Benchmark: MerkleStage-style full state-root rebuild on the device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload = benchmark config #2/#3 in miniature (BASELINE.md): a synthetic
hashed state (accounts + storage slots) is committed bottom-up with the
level-batched trie committer; every node hash runs through the batched
device keccak kernel. ``vs_baseline`` is the wall-clock speedup of the
device hasher over the numpy CPU baseline on the identical workload
(the stand-in for the reference's parallel CPU keccak path).

Env knobs: RETH_TPU_BENCH_ACCOUNTS (default 50000),
RETH_TPU_BENCH_SLOTS (default 20000 across accounts).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

# Watchdog BEFORE any jax import: the device tunnel can wedge whole
# processes (see .claude memory: axon-tunnel-pitfalls); a bench that hangs
# forever is worse than one that reports failure. Phase-aware: if the
# device run already finished, its result is reported (with vs_baseline 0
# and a note) rather than a bogus device failure.
_DEADLINE = int(os.environ.get("RETH_TPU_BENCH_TIMEOUT", "1500"))
_STATE: dict = {"phase": "startup", "device_result": None}


def _watchdog():
    time.sleep(_DEADLINE)
    dev = _STATE["device_result"]
    if dev is not None:
        print(json.dumps({
            "metric": "merkle_rebuild_keccak_per_sec", "value": dev,
            "unit": "hashes/s", "vs_baseline": 0,
            "error": f"timed out during {_STATE['phase']} after the device "
                     f"run completed (baseline unmeasured)",
        }), flush=True)
        os._exit(3)
    print(json.dumps({
        "metric": "merkle_rebuild_keccak_per_sec", "value": 0,
        "unit": "hashes/s", "vs_baseline": 0,
        "error": f"timed out during {_STATE['phase']} after {_DEADLINE}s",
    }), flush=True)
    os._exit(2)


threading.Thread(target=_watchdog, daemon=True).start()


def build_state(n_accounts: int, n_slots: int):
    from reth_tpu.primitives.rlp import encode_int, rlp_encode
    from reth_tpu.primitives.nibbles import unpack_nibbles
    from reth_tpu.primitives.types import Account
    from reth_tpu.storage.tables import encode_account

    rng = np.random.default_rng(42)
    akeys = rng.integers(0, 256, size=(n_accounts, 32), dtype=np.uint8)
    balances = rng.integers(1, 1 << 60, size=n_accounts)
    account_leaves = [
        (
            unpack_nibbles(akeys[i].tobytes()),
            encode_account(Account(nonce=int(i % 300), balance=int(balances[i]))),
        )
        for i in range(n_accounts)
    ]
    # storage tries: n_slots spread over n_accounts//10 accounts
    n_storage_accts = max(1, n_accounts // 10)
    skeys = rng.integers(0, 256, size=(n_slots, 32), dtype=np.uint8)
    svals = rng.integers(1, 1 << 60, size=n_slots)
    storage_jobs: dict[int, list] = {}
    for j in range(n_slots):
        owner = j % n_storage_accts
        storage_jobs.setdefault(owner, []).append(
            (unpack_nibbles(skeys[j].tobytes()), rlp_encode(encode_int(int(svals[j]))))
        )
    return account_leaves, list(storage_jobs.values())


def run_commit(committer, account_leaves, storage_jobs):
    jobs = [(leaves, None) for leaves in storage_jobs] + [(account_leaves, None)]
    t0 = time.time()
    results = committer.commit_many(jobs, collect_branches=False)
    dt = time.time() - t0
    hashed = sum(r.hashed_nodes for r in results)
    return results[-1].root, hashed, dt


def main():
    n_accounts = int(os.environ.get("RETH_TPU_BENCH_ACCOUNTS", "50000"))
    n_slots = int(os.environ.get("RETH_TPU_BENCH_SLOTS", "20000"))

    from reth_tpu.ops import KeccakDevice
    from reth_tpu.primitives.keccak import keccak256_batch_np
    from reth_tpu.trie.committer import TrieCommitter

    _STATE["phase"] = "state build"
    account_leaves, storage_jobs = build_state(n_accounts, n_slots)

    dev_committer = TrieCommitter()  # device hasher (TPU when attached)
    cpu_committer = TrieCommitter(hasher=keccak256_batch_np)

    # warm-up = one full untimed run, so every batch tier the measured run
    # dispatches is already compiled (XLA caches by shape in-process)
    _STATE["phase"] = "device warm-up (compiles)"
    run_commit(dev_committer, account_leaves, storage_jobs)

    _STATE["phase"] = "device run"
    root_dev, hashed_dev, dt_dev = run_commit(dev_committer, account_leaves, storage_jobs)
    _STATE["device_result"] = round(hashed_dev / dt_dev, 1)
    _STATE["phase"] = "cpu baseline"
    root_cpu, _hashed_cpu, dt_cpu = run_commit(cpu_committer, account_leaves, storage_jobs)
    if root_dev != root_cpu:
        print(
            json.dumps({"metric": "merkle_rebuild_keccak_per_sec", "value": 0,
                        "unit": "hashes/s", "vs_baseline": 0,
                        "error": "device/cpu root mismatch"}),
        )
        sys.exit(1)

    print(json.dumps({
        "metric": "merkle_rebuild_keccak_per_sec",
        "value": round(hashed_dev / dt_dev, 1),
        "unit": "hashes/s",
        "vs_baseline": round(dt_cpu / dt_dev, 3),
    }))


if __name__ == "__main__":
    main()
