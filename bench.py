"""Benchmark suite. DEFAULT mode (``RETH_TPU_BENCH_MODE`` unset or
``exec``): optimistic parallel EVM execution vs the serial interpreter —
a CPU-measurable number (engine/optimistic.py + native/evmexec.cpp), so
the perf trajectory records a real measurement even while the device
tunnel is wedged (five rounds of rc=2/value=0 taught us that lesson).
``RETH_TPU_BENCH_MODE=rebuild`` selects the original device state-root
rebuild benchmark described below; ``service``/``sparse``/``gateway``
select the other subsystem benches; ``mesh`` shards the production
turbo/fused rebuild loop over 1/2/4/8 simulated host devices (one
subprocess per mesh size, roots verified bit-identical vs the
single-device committer before any number prints, per-mesh-size
throughput + compile wall in ``per_mesh``); ``subtrie`` compares the
whole-subtrie k-level fused committer (one dispatch per k levels) to
the per-level committer at k ∈ {1,2,4,8} across 1/2/4/8 simulated
devices — dispatches/block + wall per k, roots verified bit-identical
before any number prints, and every mode's JSON line now carries
``dispatches_per_block``; ``fleet`` measures
sustained RPC throughput + p99 through the fleet gateway at 1/2/4/8
witness-fed replica subprocesses vs the single-node gateway
(duplicate-heavy + long-tail mixes, responses verified bit-identical
to an ungated dispatch before any number prints, per-size results in
``per_fleet``); ``txflow`` floods the insertion batcher with adversarial
submission mixes at 1k-50k offered tx/s and measures tx->inclusion p99 +
txs/block through the continuous block producer vs the serial
build-on-demand miner, with the hot candidate's inclusion set verified
bit-identical against a serial greedy build over a cloned pool at every
load point before any number prints (per-rate results in ``per_rate``);
``hotstate`` imports an interleaved sibling-fork stream with the
hot-state plane (cross-block trie-node cache + device digest arena) on
vs off — proof-target reduction factor as the headline, proof walls,
hit rate, H2D bytes/block and the delta-upload fraction as extras,
every payload VALID (root-checked) in both runs before any number
prints.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"backend", "vs_prev", "regression"}. ``backend`` records which plane
actually produced the number; ``vs_prev`` compares against the trailing
last-N-good-runs baseline for the same metric+mode+backend+warmup key
(health.BenchBaselineStore, persisted at RETH_TPU_BENCH_BASELINE_STORE
or <repo>/.bench_baselines.json) and ``regression`` flips true when the
run drops under RETH_TPU_BENCH_REGRESSION_THRESHOLD (default 0.8x) of
it — RETH_TPU_BENCH_STRICT=1 turns that into rc=3. A
wedged/absent tunnel no longer yields rc=2 with value 0 — the rebuild
mode records the OVERLAPPED rebuild pipeline's CPU rate
(trie/turbo.RebuildPipeline: pooled native sweeps + cross-subtrie level
packing + resident digest arena) with ``vs_baseline`` = speedup over
the seed's serial per-subtrie chunked path, roots bit-identical, and
exits 0.

Workload = benchmark config #2/#3 in miniature (BASELINE.md): a synthetic
hashed state (accounts + storage slots) is committed bottom-up with the
TURBO committer — C++ structure sweep (native/triebuild.cpp), packed/bitmap
level arrays, device-resident digest buffer, zero mid-commit D2H
(reth_tpu/trie/turbo.py + reth_tpu/ops/fused_commit.py). ``vs_baseline``
is the wall-clock speedup over the SAME turbo pipeline with the numpy CPU
hashing backend — an honest strong baseline standing in for the
reference's rayon keccak path (reference
crates/stages/stages/src/stages/hashing_account.rs:29-32).

Hardening (round-1/2 postmortems, VERDICT.md "What's weak" #1):
- A fail-fast tunnel health probe runs FIRST in a subprocess with a hard
  per-attempt budget, RETRIED (default 4 attempts x 120 s, 45 s apart —
  worst case ~10 min of the watchdog window) so one wedged minute doesn't
  kill the round's headline; a persistently wedged tunnel still yields a
  diagnostic JSON well inside the watchdog. If the probe only succeeds
  late, the workload shrinks so the measured run still fits.
- The fused committer at a forced single batch tier keeps the XLA program
  count <= ~4 (one compile storm wedged the round-1 tunnel for good).
- The phase-aware watchdog still guarantees one JSON line no matter what.

Performance model (measured): the axon tunnel moves program-consumed
inputs at ~25 MB/s with ~40-70 ms per-transfer latency, so the device
wall is dominated by wire bytes/leaf (~95 B) — the whole-commit mega
dispatch (ops/fused_commit.py MegaFusedEngine) exists to pay ONE
transfer + ONE program per commit. Larger workloads amortize the fixed
costs, so the default size is chosen where the ratio approaches its
wire-bound asymptote while still finishing well under the watchdog.

Env knobs: RETH_TPU_BENCH_ACCOUNTS (default 150000), RETH_TPU_BENCH_SLOTS
(default 60000), RETH_TPU_BENCH_TIER (fused batch tier, default 16384),
RETH_TPU_BENCH_TIMEOUT (watchdog, default 1200), RETH_TPU_PROBE_TIMEOUT
(per-attempt probe budget, default 120), RETH_TPU_PROBE_ATTEMPTS
(default 4), RETH_TPU_PROBE_GAP (seconds between attempts, default 45).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

_DEADLINE = int(os.environ.get("RETH_TPU_BENCH_TIMEOUT", "1200"))
_STATE: dict = {"phase": "startup", "device_result": None}


def _flight_excerpt(n: int = 24) -> list:
    """Tail of the flight recorder (probe outcomes, fault events, recent
    spans) — the trail the wedged-tunnel zeros never left behind."""
    try:
        from reth_tpu import tracing

        return [{k: rec.get(k) for k in
                 ("kind", "target", "name", "ts", "dur_ms", "fields",
                  "error")}
                for rec in tracing.flight_snapshot(n)]
    except Exception:  # noqa: BLE001 — diagnostics only
        return []


def _compile_split() -> dict:
    """compile_wall_s vs steady-state: the per-shape first-call walls the
    compile tracker collected (metrics.DeviceCompileTracker) — every mode
    reports the split so a compile storm can't masquerade as slow
    hashing."""
    try:
        from reth_tpu.metrics import compile_tracker

        t = compile_tracker.totals()
        return {"compile_wall_s": t["compile_wall_s"],
                "compiled_shapes": t["shapes"]}
    except Exception:  # noqa: BLE001 — diagnostics only
        return {"compile_wall_s": 0.0, "compiled_shapes": 0}


def _assess_vs_prev(line, error) -> None:
    """Perf-regression sentinel (health.BenchBaselineStore): every line
    gains ``vs_prev`` (value / median of the trailing last-N GOOD runs
    for the same metric+mode+backend+warmup-state key) and a loud
    ``regression`` flag — so a real throughput drop can't hide behind a
    wedged tunnel's ``vs_baseline: 0``. Good runs append to the store;
    error/zero lines only read it. Never fatal to the bench."""
    try:
        from reth_tpu.health import BenchBaselineStore

        mode = os.environ.get("RETH_TPU_BENCH_MODE", "exec")
        threshold = float(
            os.environ.get("RETH_TPU_BENCH_REGRESSION_THRESHOLD", "0.8"))
        store = BenchBaselineStore()
        value = line["value"]
        good = not error and isinstance(value, (int, float)) and value > 0
        if good:
            verdict = store.assess(line["metric"], mode, line["backend"],
                                   line["warmup_state"], float(value),
                                   threshold=threshold)
            store.record(line["metric"], mode, line["backend"],
                         line["warmup_state"], float(value),
                         vs_baseline=line.get("vs_baseline"))
        else:
            verdict = {"vs_prev": None, "regression": False,
                       "baseline_n": 0, "baseline": None}
        line["vs_prev"] = verdict["vs_prev"]
        line["regression"] = verdict["regression"]
        line["baseline_n"] = verdict["baseline_n"]
        if verdict["baseline"] is not None:
            line["baseline_prev"] = verdict["baseline"]
        if verdict["regression"]:
            print(f"PERF REGRESSION: {line['metric']} = {value} "
                  f"{line['unit']} is {verdict['vs_prev']}x the trailing "
                  f"baseline ({verdict['baseline']} over "
                  f"{verdict['baseline_n']} runs)", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 — the sentinel never fails a bench
        line.setdefault("vs_prev", None)
        line.setdefault("regression", False)
        line["baseline_error"] = f"{type(e).__name__}: {e}"


def _emit(value, vs_baseline, error=None, exit_code=None, **extra):
    line = {
        "metric": _STATE.get("metric", "merkle_rebuild_keccak_per_sec"),
        "value": value,
        "unit": _STATE.get("unit", "hashes/s"),
        "vs_baseline": vs_baseline,
        "backend": _STATE.get("backend", "unknown"),
        # warm-up attribution rides on EVERY line (incl. watchdog/error
        # lines): a wedged-tunnel zero without a warmup_state field is how
        # five rounds of BENCH data became unreadable. Resolved LIVE from
        # the manager so even a line emitted mid-warm-up (watchdog fired
        # while a compile wedged) records which shape it died on.
        "warmup_state": (_STATE["warmup_mgr"].snapshot()
                         if _STATE.get("warmup_mgr") is not None
                         else _STATE.get("warmup_state", "off")),
        "compile_cache": _STATE.get("compile_cache", "off"),
    }
    line.update(_compile_split())
    # dispatch accounting rides on EVERY line (the BENCH trajectory was
    # empty on this axis): the last fused commit's device-dispatch count,
    # 0 when no fused commit ran this process
    try:
        from reth_tpu.metrics import fused_metrics

        line.setdefault("dispatches_per_block",
                        (fused_metrics.last or {}).get("dispatches", 0))
    except Exception:  # noqa: BLE001 — diagnostics only
        line.setdefault("dispatches_per_block", 0)
    # cross-block pipeline attribution rides on EVERY line: depth 1 and
    # overlap 0 when no pipelined import ran this process
    try:
        from reth_tpu.metrics import block_pipeline_metrics

        bp = block_pipeline_metrics.last or {}
        line.setdefault("pipeline_depth", bp.get("depth") or 1)
        line.setdefault("overlap_fraction", round(bp.get("overlap") or 0.0, 4))
    except Exception:  # noqa: BLE001 — diagnostics only
        line.setdefault("pipeline_depth", 1)
        line.setdefault("overlap_fraction", 0.0)
    if error:
        line["error"] = error
        line["flight_recorder"] = _flight_excerpt()
    elif extra.get("device_unavailable"):
        line["flight_recorder"] = _flight_excerpt()
    line.update(extra)
    _assess_vs_prev(line, error)
    print(json.dumps(line), flush=True)
    if exit_code is not None:
        if (line.get("regression")
                and os.environ.get("RETH_TPU_BENCH_STRICT")
                and exit_code == 0):
            # strict mode: a regression vs the trailing baseline is a
            # FAILURE, not a footnote (opt-in: the driver's rc contract
            # treats nonzero as harness breakage, so default stays 0)
            os._exit(3)
        os._exit(exit_code)


def _watchdog():
    time.sleep(_DEADLINE)
    dev = _STATE["device_result"]
    # rc=0 either way: a wedged device is a DIAGNOSED outcome, not a
    # harness failure (five rounds of rc=2/value=0 taught us that an
    # unreadable exit erases the trajectory — the error field + flight
    # recorder excerpt carry the postmortem now)
    if dev is not None:
        _emit(dev, 0, error=f"timed out during {_STATE['phase']} after the device run "
                            f"completed (baseline unmeasured)", exit_code=0)
    _emit(0, 0, error=f"timed out during {_STATE['phase']} after {_DEADLINE}s",
          exit_code=0)


threading.Thread(target=_watchdog, daemon=True).start()


def probe_tunnel() -> str | None:
    """Fail-fast health check, RETRIED a few times spread over the first
    half of the watchdog window (round-2 postmortem: one wedged minute
    killed the whole round's headline — VERDICT round 2, next-round #1a).
    Returns None when healthy, else a diagnostic string after the last
    attempt.

    The probe itself now lives in the library
    (reth_tpu/ops/supervisor.py:probe_device) — the SAME implementation the
    node's ``--hasher auto`` supervisor runs at startup and on half-open
    re-probes, so bench and runtime can't drift apart. (Still no
    `jax_compilation_cache_dir` in the child — the persistent compile cache
    deadlocks the first jit over the axon tunnel, measured round 2.)"""
    from reth_tpu.ops.supervisor import FaultInjector, probe_device_retrying

    def _phase(i, attempts):
        _STATE["phase"] = f"tunnel health probe (attempt {i}/{attempts})"

    # RETH_TPU_FAULT_PROBE_FAIL drills the wedged-tunnel path end-to-end:
    # injected probe failure -> CPU-fallback measurement -> rc=0
    result = probe_device_retrying(on_attempt=_phase,
                                   injector=FaultInjector.from_env())
    return None if result.ok else result.diag


def build_state(n_accounts: int, n_slots: int):
    """MerkleStage-chunk-shaped jobs: per-account storage tries (committed
    at depth 0) + the account trie as 256 two-nibble-prefix subtries
    (committed at ``start_depth=2``) — exactly what ``_account_chunk``
    feeds the committer. Returns (storage_jobs, account_prefix_jobs)."""
    from reth_tpu.primitives.rlp import encode_int, rlp_encode
    from reth_tpu.primitives.types import Account
    from reth_tpu.storage.tables import encode_account

    rng = np.random.default_rng(42)
    akeys = rng.integers(0, 256, size=(n_accounts, 32), dtype=np.uint8)
    akeys = np.unique(akeys.view("S32").ravel()).view(np.uint8).reshape(-1, 32)
    n_accounts = len(akeys)
    balances = rng.integers(1, 1 << 60, size=n_accounts)
    avals = [
        encode_account(Account(nonce=int(i % 300), balance=int(balances[i])))
        for i in range(n_accounts)
    ]
    account_jobs = []
    for pfx in range(256):
        sel = np.nonzero(akeys[:, 0] == pfx)[0]
        if len(sel):
            account_jobs.append((akeys[sel], [avals[i] for i in sel]))
    # storage tries: n_slots spread over n_accounts//10 accounts
    n_storage_accts = max(1, n_accounts // 10)
    skeys = rng.integers(0, 256, size=(n_slots, 32), dtype=np.uint8)
    svals = [rlp_encode(encode_int(int(v))) for v in rng.integers(1, 1 << 60, size=n_slots)]
    storage_jobs = []
    for owner in range(n_storage_accts):
        sel = np.arange(owner, n_slots, n_storage_accts)
        if len(sel):
            storage_jobs.append((skeys[sel], [svals[i] for i in sel]))
    return storage_jobs, account_jobs


def run_rebuild(committer, storage_jobs, account_jobs, pipelined: bool):
    """One full-rebuild pass. ``pipelined=False`` is the seed's SERIAL
    chunked path: storage tries in one batched call, then one commit per
    account prefix subtrie (sweep → hash → fetch with nothing overlapped).
    ``pipelined=True`` routes both phases through the overlapped pipeline
    (pooled sweeps + cross-subtrie level packing + resident arena)."""
    t0 = time.time()
    if pipelined:
        res = committer.commit_hashed_pipelined(storage_jobs)
        res += committer.commit_hashed_pipelined(account_jobs, start_depth=2)
    else:
        res = committer.commit_hashed_many(storage_jobs)
        for job in account_jobs:
            res += committer.commit_hashed_many([job], start_depth=2)
    dt = time.time() - t0
    hashed = sum(r.hashed_nodes for r in res)
    return [r.root for r in res], hashed, dt


def run_cpu_fallback(n_accounts: int, n_slots: int, diag: str) -> None:
    """Device unavailable: record a CPU(numpy) measurement instead of the
    old rc=2 / value=0 (five rounds of wedged-tunnel zeros made the
    trajectory unreadable — BENCH_r05 postmortem). The headline is the
    OVERLAPPED pipeline's rate; ``vs_baseline`` is its speedup over the
    seed's serial chunked path on the same box, roots bit-identical."""
    from reth_tpu.trie.turbo import TurboCommitter

    _STATE["backend"] = "numpy"
    _STATE["phase"] = "state build (cpu fallback)"
    storage_jobs, account_jobs = build_state(n_accounts, n_slots)
    committer = TurboCommitter(backend="numpy")

    _STATE["phase"] = "cpu serial chunked rebuild"
    roots_ser, hashed, dt_serial = run_rebuild(
        committer, storage_jobs, account_jobs, pipelined=False)
    _STATE["phase"] = "cpu pipelined rebuild"
    roots_pipe, hashed_p, dt_pipe = run_rebuild(
        committer, storage_jobs, account_jobs, pipelined=True)
    if roots_ser != roots_pipe:
        _emit(0, 0, error="pipelined/serial root mismatch", exit_code=1)
    _STATE["device_result"] = round(hashed_p / dt_pipe, 1)
    _emit(round(hashed_p / dt_pipe, 1), round(dt_serial / dt_pipe, 3),
          device_unavailable=diag,
          serial_wall_s=round(dt_serial, 3),
          pipelined_wall_s=round(dt_pipe, 3),
          serial_hashes_per_sec=round(hashed / dt_serial, 1),
          exit_code=0)


def run_service_mode() -> None:
    """RETH_TPU_BENCH_MODE=service: coalesced small-batch throughput vs
    per-call dispatch — the hash-service headline (ops/hash_service.py).

    Workload: T concurrent clients each issuing many SMALL hash requests
    (the SparseRootTask / proof shape the service exists for). Baseline =
    every request dispatched directly on the backend (per-call overhead,
    tiny batches); measured = the same requests through the service's
    coalescing window (continuous batching into full-rate dispatches).
    Runs on the device when the tunnel probes healthy, else the numpy
    twin — either way one JSON line with the speedup and the measured
    coalesce factor. Env: RETH_TPU_BENCH_SVC_CLIENTS (default 8),
    RETH_TPU_BENCH_SVC_REQS (requests/client, default 300),
    RETH_TPU_BENCH_SVC_KEYS (keys/request, default 4)."""
    import numpy as _np

    from reth_tpu.metrics import MetricsRegistry
    from reth_tpu.ops.hash_service import HashService
    from reth_tpu.primitives.keccak import keccak256_batch_np

    clients = int(os.environ.get("RETH_TPU_BENCH_SVC_CLIENTS", "8"))
    reqs = int(os.environ.get("RETH_TPU_BENCH_SVC_REQS", "300"))
    keys = int(os.environ.get("RETH_TPU_BENCH_SVC_KEYS", "4"))
    _STATE["metric"] = "hash_service_small_batch_per_sec"
    _STATE["phase"] = "service bench probe"
    diag = probe_tunnel()
    if diag is None:
        from reth_tpu.ops.keccak_jax import KeccakDevice

        _STATE["backend"] = "device"
        backend = KeccakDevice(min_tier=1024, block_tier=4).hash_batch
    else:
        _STATE["backend"] = "numpy"
        backend = keccak256_batch_np
    rng = _np.random.default_rng(7)
    workload = [
        [rng.integers(0, 256, size=64, dtype=_np.uint8).tobytes()
         for _ in range(keys)]
        for _ in range(clients * reqs)
    ]
    lanes = ("live", "payload", "rebuild", "proof")

    def run_clients(dispatch_fn) -> float:
        errs: list = []

        def worker(c):
            try:
                for i in range(reqs):
                    dispatch_fn(lanes[c % 4], workload[c * reqs + i])
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errs.append(e)

        ts = [threading.Thread(target=worker, args=(c,)) for c in range(clients)]
        t0 = time.time()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        if errs:
            raise errs[0]
        return time.time() - t0

    total = clients * reqs * keys
    _STATE["phase"] = "per-call baseline (direct dispatch)"
    backend(workload[0])  # warm compiles out of the measured window
    dt_direct = run_clients(lambda lane, msgs: backend(msgs))
    _STATE["phase"] = "service run (coalesced)"
    svc = HashService(backend=backend, registry=MetricsRegistry())
    try:
        dt_svc = run_clients(lambda lane, msgs: svc.hash(lane, msgs))
        factor = round(svc.coalesce_factor(), 2)
        dispatches = svc.dispatches
    finally:
        svc.stop()
    _STATE["device_result"] = round(total / dt_svc, 1)
    _emit(round(total / dt_svc, 1), round(dt_direct / dt_svc, 3),
          coalesce_factor=factor, service_dispatches=dispatches,
          requests=clients * reqs, keys_per_request=keys,
          percall_wall_s=round(dt_direct, 3), service_wall_s=round(dt_svc, 3),
          percall_hashes_per_sec=round(total / dt_direct, 1),
          **({"device_unavailable": diag} if diag else {}),
          exit_code=0)


def run_gateway_mode() -> None:
    """RETH_TPU_BENCH_MODE=gateway: coalesced vs naive requests/s under a
    duplicate-heavy read workload — the RPC serving gateway headline
    (rpc/gateway.py).

    Workload: T client threads each issuing many ``eth_call``-shaped
    requests drawn from a SMALL key pool (trackers and wallets hammer the
    same few reads), against a handler doing real CPU work (a batched
    keccak over params-derived messages — the CPU-fallback path, so this
    reports a real number with or without a device). Baseline = the same
    requests through an ungated RpcServer (every duplicate recomputes
    under the coarse handler lock); measured = one gateway coalescing
    in-flight duplicates and serving repeats from the head-scoped
    response cache. Responses are checked bit-identical to the naive
    path before the number is emitted. Env: RETH_TPU_BENCH_GW_CLIENTS
    (default 8), RETH_TPU_BENCH_GW_REQS (requests/client, default 150),
    RETH_TPU_BENCH_GW_KEYS (distinct request keys, default 8),
    RETH_TPU_BENCH_GW_WORK (keccak msgs per handler call, default 600)."""
    from reth_tpu.metrics import MetricsRegistry
    from reth_tpu.primitives.keccak import keccak256_batch_np
    from reth_tpu.rpc.gateway import RpcGateway
    from reth_tpu.rpc.server import RpcServer

    clients = int(os.environ.get("RETH_TPU_BENCH_GW_CLIENTS", "8"))
    reqs = int(os.environ.get("RETH_TPU_BENCH_GW_REQS", "150"))
    n_keys = int(os.environ.get("RETH_TPU_BENCH_GW_KEYS", "8"))
    work = int(os.environ.get("RETH_TPU_BENCH_GW_WORK", "600"))
    _STATE["metric"] = "gateway_requests_per_sec"
    _STATE["unit"] = "requests/s"
    _STATE["backend"] = "cpu"

    def handler(*params):
        seed = json.dumps(params, sort_keys=True).encode()
        msgs = [seed + i.to_bytes(4, "big") for i in range(work)]
        return {"data": "0x" + keccak256_batch_np(msgs)[0].hex()}

    def make_server(gateway):
        srv = RpcServer(gateway=gateway)
        srv.register_method("eth_call", handler)
        return srv

    bodies = [json.dumps({
        "jsonrpc": "2.0", "id": 7, "method": "eth_call",
        "params": [{"to": f"0x{k:040x}", "data": "0xdeadbeef"}, "latest"],
    }).encode() for k in range(n_keys)]

    def run_clients(srv) -> float:
        errs: list = []

        def worker(c):
            try:
                rng = np.random.default_rng(c)
                for i in range(reqs):
                    srv.handle(bodies[int(rng.integers(0, n_keys))])
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errs.append(e)

        ts = [threading.Thread(target=worker, args=(c,))
              for c in range(clients)]
        t0 = time.time()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        if errs:
            raise errs[0]
        return time.time() - t0

    total = clients * reqs
    _STATE["phase"] = "naive baseline (ungated dispatch)"
    naive = make_server(None)
    naive.handle(bodies[0])  # warm allocations out of the measured window
    dt_naive = run_clients(naive)
    _STATE["phase"] = "gateway run (coalesced + cached)"
    gw = RpcGateway(head_supplier=lambda: b"bench-head",
                    registry=MetricsRegistry())
    gated = make_server(gw)
    dt_gated = run_clients(gated)
    _STATE["phase"] = "response parity check"
    for body in bodies:
        if gated.handle(body) != naive.handle(body):
            _emit(0, 0, error="gated/naive response mismatch", exit_code=1)
    snap = gw.snapshot()
    _STATE["device_result"] = round(total / dt_gated, 1)
    _emit(round(total / dt_gated, 1), round(dt_naive / dt_gated, 3),
          coalesce_factor=snap["coalesce_factor"],
          cache_hit_rate=snap["cache_hit_rate"],
          executions=snap["executions"], requests=total,
          distinct_keys=n_keys, work_msgs_per_call=work,
          naive_wall_s=round(dt_naive, 3), gateway_wall_s=round(dt_gated, 3),
          naive_requests_per_sec=round(total / dt_naive, 1),
          exit_code=0)


def build_sparse_state(n_tries: int, slots: int, dirty: int, seed: int = 3):
    """One storage-heavy live-tip block in miniature: a SparseStateTrie
    with ``n_tries`` fully-revealed storage tries x ``slots`` slots plus
    matching account leaves, committed once (clean refs — the preserved
    cross-block state), then ``dirty`` slot writes + a few deletes/wipes
    per-trie and account churn: exactly the dirty set finish() sees."""
    import numpy as _np

    from reth_tpu.trie.sparse import SparseStateTrie, SparseTrie
    from reth_tpu.primitives.keccak import keccak256_batch_np

    rng = _np.random.default_rng(seed)
    st = SparseStateTrie()
    owners = []
    slot_keys: dict[bytes, list[bytes]] = {}
    for _ in range(n_tries):
        ha = bytes(rng.integers(0, 256, 32, dtype=_np.uint8))
        owners.append(ha)
        t = st.storage_trie(ha)
        keys = [bytes(rng.integers(0, 256, 32, dtype=_np.uint8))
                for _ in range(slots)]
        slot_keys[ha] = keys
        for k in keys:
            t.update(k, bytes(rng.integers(1, 256, 8, dtype=_np.uint8)))
        st.update_account(ha, b"account-leaf-" + ha)
    st.root(keccak256_batch_np)  # clean baseline (serial; untimed)
    # the block's dirty set
    for i, ha in enumerate(owners):
        t = st.storage_trie(ha)
        keys = slot_keys[ha]
        for j in range(dirty):
            t.update(keys[j % len(keys)],
                     bytes(rng.integers(1, 256, 8, dtype=_np.uint8)))
        t.delete(keys[-1])
        if i % 16 == 15:  # a few SELFDESTRUCT wipes
            st.storage_tries[ha] = SparseTrie()
        st.update_account(ha, b"post-leaf-" + ha)
    return st


def run_sparse_mode() -> None:
    """RETH_TPU_BENCH_MODE=sparse: storage-heavy live-tip ``finish()``
    commit latency — the PARALLEL packed path (cross-trie per-depth
    dispatch fusion + lower-subtrie encode pool,
    trie/sparse.py ParallelSparseCommitter) vs the serial per-trie
    ``root_hash_compute`` loop the seed ran. Roots must be bit-identical;
    ``vs_baseline`` = serial wall / parallel wall. Runs on the device
    when the tunnel probes healthy, else the numpy twin (the established
    CPU-fallback "backend" reporting). Env: RETH_TPU_BENCH_SPARSE_TRIES
    (default 192), RETH_TPU_BENCH_SPARSE_SLOTS (slots/trie, default 64),
    RETH_TPU_BENCH_SPARSE_DIRTY (dirty writes/trie, default 16),
    RETH_TPU_SPARSE_WORKERS (encode-pool width, default auto)."""
    from reth_tpu.primitives.keccak import keccak256_batch_np
    from reth_tpu.trie.sparse import ParallelSparseCommitter

    n_tries = int(os.environ.get("RETH_TPU_BENCH_SPARSE_TRIES", "192"))
    slots = int(os.environ.get("RETH_TPU_BENCH_SPARSE_SLOTS", "64"))
    dirty = int(os.environ.get("RETH_TPU_BENCH_SPARSE_DIRTY", "16"))
    _STATE["metric"] = "sparse_commit_hashes_per_sec"
    _STATE["phase"] = "sparse bench probe"
    diag = probe_tunnel()
    if diag is None:
        from reth_tpu.ops.keccak_jax import KeccakDevice

        _STATE["backend"] = "device"
        hasher = KeccakDevice(min_tier=1024, block_tier=4).hash_batch
    else:
        _STATE["backend"] = "numpy"
        hasher = keccak256_batch_np

    _STATE["phase"] = "sparse state build (serial pass)"
    st_serial = build_sparse_state(n_tries, slots, dirty)
    t0 = time.time()
    root_serial = st_serial.root(hasher)
    dt_serial = time.time() - t0

    _STATE["phase"] = "sparse state build (parallel pass)"
    st_par = build_sparse_state(n_tries, slots, dirty)
    committer = ParallelSparseCommitter()
    t0 = time.time()
    root_par = st_par.root(hasher, committer=committer)
    dt_par = time.time() - t0
    if root_serial != root_par:
        _emit(0, 0, error="parallel/serial sparse root mismatch", exit_code=1)
    stats = committer.last or {}
    hashed = stats.get("hashed", 0)
    _STATE["device_result"] = round(hashed / dt_par, 1)
    _emit(round(hashed / dt_par, 1), round(dt_serial / dt_par, 3),
          serial_wall_s=round(dt_serial, 4),
          parallel_wall_s=round(dt_par, 4),
          tries=stats.get("tries"), levels_packed=stats.get("levels"),
          dispatches=stats.get("dispatches"),
          encode_chunks=stats.get("encode_chunks"),
          sparse_workers=committer.workers,
          **({"device_unavailable": diag} if diag else {}),
          exit_code=0)


def _exec_bench_block(n_txs: int, conflict_rate: float, reps: int):
    """One synthetic block: every tx calls a compute-heavy store contract
    (``reps`` unrolled MUL/ADD units then SSTORE slot0 — natively
    executable, interpreter-expensive). A ``conflict_rate`` fraction of
    ranks call ONE shared contract (write-after-write on the same slot —
    those ranks invalidate and re-run serially); the rest each own a
    private contract, so their writes are fully disjoint. Senders are
    synthetic (the executor trusts the provided sender list), so the
    workload needs no signing."""
    from reth_tpu.evm.executor import InMemoryStateSource
    from reth_tpu.primitives import Account
    from reth_tpu.primitives.keccak import keccak256
    from reth_tpu.primitives.types import Block, Header, Transaction

    # PUSH0 CALLDATALOAD; reps x (PUSH1 31 MUL PUSH1 7 ADD); DUP1 PUSH0
    # SSTORE; STOP — seed-dependent compute chain ending in one store
    code = (b"\x5f\x35" + bytes.fromhex("601f02600701") * reps
            + bytes.fromhex("805f5500"))
    ch = keccak256(code)
    senders = [bytes([0xA0]) + i.to_bytes(19, "big") for i in range(n_txs)]
    accounts = {s: Account(balance=10**20) for s in senders}
    shared = b"\x5e" * 20
    accounts[shared] = Account(code_hash=ch)
    txs = []
    stride = int(1 / conflict_rate) if conflict_rate else 0
    for i in range(n_txs):
        if stride and i % stride == 0:
            to = shared  # conflicting rank: same contract, same slot
        else:
            to = bytes([0x5C]) + i.to_bytes(19, "big")
            accounts[to] = Account(code_hash=ch)
        txs.append(Transaction(
            tx_type=2, chain_id=1, nonce=0, max_fee_per_gas=100 * 10**9,
            max_priority_fee_per_gas=10**9, gas_limit=500_000, to=to,
            value=0, data=(0xBEEF00 + i).to_bytes(32, "big")))
    header = Header(number=1, gas_limit=10**9, base_fee_per_gas=7,
                    beneficiary=b"\xc0" * 20)
    block = Block(header, tuple(txs), (), ())

    def mk_source():
        return InMemoryStateSource(dict(accounts), codes={ch: code})

    return block, senders, mk_source


def run_exec_mode() -> None:
    """RETH_TPU_BENCH_MODE=exec (the DEFAULT): optimistic parallel block
    execution (engine/optimistic.py — Block-STM-style native speculation
    + read-set validation + async storage prefetch) vs the serial
    ``BlockExecutor`` interpreter, parameterized by conflict rate.
    Receipts and post state are verified bit-identical before any number
    is emitted. Headline = txs/s at 0% conflicts; ``vs_baseline`` = the
    serial wall over the optimistic wall on that workload. Extras carry
    the 10%/50%-conflict points and a workers=1 run (scheduler overhead
    floor / thread-scaling reference). Env: RETH_TPU_BENCH_EXEC_TXS
    (default 384), RETH_TPU_BENCH_EXEC_WORKERS (default 8),
    RETH_TPU_BENCH_EXEC_REPS (compute units per tx, default 400)."""
    from reth_tpu.engine.optimistic import execute_block_optimistic
    from reth_tpu.evm import BlockExecutor, EvmConfig

    n_txs = int(os.environ.get("RETH_TPU_BENCH_EXEC_TXS", "384"))
    workers = int(os.environ.get("RETH_TPU_BENCH_EXEC_WORKERS", "8"))
    reps = int(os.environ.get("RETH_TPU_BENCH_EXEC_REPS", "400"))
    cfg = EvmConfig(chain_id=1)
    _STATE["metric"] = "exec_parallel_txs_per_sec"
    _STATE["unit"] = "txs/s"
    _STATE["backend"] = "cpu"
    per_rate = {}
    headline = None
    for rate in (0.0, 0.1, 0.5):
        _STATE["phase"] = f"exec bench: build block ({rate:.0%} conflicts)"
        block, senders, mk_source = _exec_bench_block(n_txs, rate, reps)
        # warm: native library build + first-call allocations stay out of
        # the measured walls
        execute_block_optimistic(mk_source(), block, senders, cfg,
                                 max_workers=workers)
        _STATE["phase"] = f"exec bench: serial pass ({rate:.0%} conflicts)"
        t0 = time.time()
        serial = BlockExecutor(mk_source(), cfg).execute(block, senders)
        dt_serial = time.time() - t0
        _STATE["phase"] = f"exec bench: optimistic pass ({rate:.0%})"
        t0 = time.time()
        out, stats = execute_block_optimistic(mk_source(), block, senders,
                                              cfg, max_workers=workers)
        dt_opt = time.time() - t0
        _STATE["phase"] = f"exec bench: verify receipts ({rate:.0%})"
        if [r.encode_2718() for r in serial.receipts] != \
                [r.encode_2718() for r in out.receipts] or \
                serial.post_accounts != out.post_accounts or \
                serial.post_storage != out.post_storage or \
                serial.gas_used != out.gas_used:
            _emit(0, 0, error=f"optimistic/serial output mismatch at "
                              f"{rate:.0%} conflicts", exit_code=1)
        if stats.get("native"):
            _STATE["backend"] = "native-cpu"
        per_rate[f"{rate:.0%}"] = {
            "serial_wall_s": round(dt_serial, 4),
            "optimistic_wall_s": round(dt_opt, 4),
            "speedup": round(dt_serial / dt_opt, 3),
            "txs_per_sec": round(n_txs / dt_opt, 1),
            "serial_txs_per_sec": round(n_txs / dt_serial, 1),
            "rounds": stats.get("rounds"), "native": stats.get("native"),
            "conflicts": stats.get("conflicts"),
            "serial_reruns": stats.get("serial_rerun"),
            "prefetched": stats.get("prefetched"),
            "fallback": stats.get("fallback"),
        }
        if rate == 0.0:
            headline = (round(n_txs / dt_opt, 1),
                        round(dt_serial / dt_opt, 3))
    # scheduler overhead floor: same 0%-conflict block at ONE worker
    _STATE["phase"] = "exec bench: workers=1 reference"
    block, senders, mk_source = _exec_bench_block(n_txs, 0.0, reps)
    t0 = time.time()
    execute_block_optimistic(mk_source(), block, senders, cfg, max_workers=1)
    per_rate["0%"]["workers1_wall_s"] = round(time.time() - t0, 4)
    _STATE["device_result"] = headline[0]
    _emit(headline[0], headline[1], txs=n_txs, workers=workers,
          compute_reps=reps, conflict_rates=per_rate,
          receipts_identical=True, exit_code=0)


def run_import_mode():
    """RETH_TPU_BENCH_MODE=import: cross-block pipelined import
    (engine/block_pipeline.py — execute block N+1 over N's frozen commit
    window while N's fused root dispatches run) vs strictly serial
    import of the SAME chain through a depth-1 tree. Per-block state
    roots, receipts and senders are verified bit-identical BEFORE any
    number is emitted. Headline = blocks/s through the pipelined tree;
    ``vs_baseline`` = serial wall over pipelined wall. Extras carry the
    exec/commit leg walls, ``overlap_fraction`` (share of speculative
    exec that ran inside the parent's commit window), the abort ladder
    counters, and the sustained-wall target (wall/block < max leg —
    reachable only where the commit leg is device-bound; on a 1-core
    host the overlap is time-sliced and the fraction is still the
    honest signal). Env: RETH_TPU_BENCH_IMPORT_BLOCKS (default 8),
    RETH_TPU_BENCH_IMPORT_TXS (default 24),
    RETH_TPU_BENCH_IMPORT_WALLETS (default 48)."""
    from reth_tpu.engine import EngineTree
    from reth_tpu.engine.block_pipeline import import_chain
    from reth_tpu.engine.tree import PayloadStatusKind
    from reth_tpu.primitives import Account
    from reth_tpu.primitives.keccak import keccak256_batch_np
    from reth_tpu.storage import MemDb, ProviderFactory
    from reth_tpu.storage.genesis import init_genesis
    from reth_tpu.testing import ChainBuilder, Wallet
    from reth_tpu.trie import TrieCommitter

    n_blocks = int(os.environ.get("RETH_TPU_BENCH_IMPORT_BLOCKS", "8"))
    n_txs = int(os.environ.get("RETH_TPU_BENCH_IMPORT_TXS", "24"))
    n_wallets = int(os.environ.get("RETH_TPU_BENCH_IMPORT_WALLETS", "48"))
    _STATE["metric"] = "import_pipelined_blocks_per_sec"
    _STATE["unit"] = "blocks/s"

    cpu = TrieCommitter(hasher=keccak256_batch_np)
    committer = TrieCommitter()  # device/jitted keccak where available
    _STATE["backend"] = getattr(committer, "backend", None) or "device"

    def make_chain():
        ws = [Wallet(0x1000 + i) for i in range(n_wallets)]
        genesis = {w.address: Account(balance=10**21) for w in ws}
        b = ChainBuilder(genesis, committer=cpu)
        half = n_wallets // 2
        for i in range(n_blocks):
            # disjoint senders -> receivers; receivers spend next block,
            # so every block N+1 reads block N's uncommitted writes
            send, recv = (ws[:half], ws[half:]) if i % 2 == 0 else \
                         (ws[half:], ws[:half])
            b.build_block([send[j % half].transfer(
                recv[j % half].address, 10**14 + i * n_txs + j)
                for j in range(n_txs)])
        f = ProviderFactory(MemDb())
        init_genesis(f, b.genesis, b.accounts_at_genesis, committer=cpu)
        return b, f

    def run(depth, overlap):
        b, f = make_chain()
        tree = EngineTree(f, committer=committer,
                          persistence_threshold=10**9, pipeline_depth=depth)
        t0 = time.time()
        sts = import_chain(tree, b.blocks[1:], fcu=False, overlap=overlap)
        return b, tree, time.time() - t0, sts

    _STATE["phase"] = "import bench: warm-up chain"
    run(1, False)  # jit compiles + first-call allocations off the walls
    _STATE["phase"] = "import bench: serial import"
    b_s, t_serial, serial_wall, st_s = run(1, False)
    _STATE["phase"] = "import bench: pipelined import"
    b_p, t_piped, piped_wall, st_p = run(2, True)

    _STATE["phase"] = "import bench: verify roots bit-identical"
    if not all(s.status is PayloadStatusKind.VALID for s in st_s + st_p):
        _emit(0, 0, error="import bench: non-VALID payload status",
              exit_code=1)
    for i, (bs, bp_) in enumerate(zip(b_s.blocks[1:], b_p.blocks[1:])):
        es, ep = t_serial.blocks.get(bs.hash), t_piped.blocks.get(bp_.hash)
        if es is None or ep is None or \
                es.block.header.state_root != ep.block.header.state_root or \
                es.receipts != ep.receipts or es.senders != ep.senders:
            _emit(0, 0, error=f"import bench: serial/pipelined divergence "
                              f"at block {i + 1}", exit_code=1)

    stats = t_piped.pipeline.stats_snapshot()
    if stats["leases_active"]:
        _emit(0, 0, error=f"import bench: {stats['leases_active']} leaked "
                          f"sub-mesh leases", exit_code=1)
    adopted = stats["adopted"]
    exec_pb = stats["exec_wall_s"] / max(1, adopted + 1)
    commit_pb = stats["commit_wall_s"] / max(1, adopted + 1)
    sustained_pb = piped_wall / n_blocks
    max_leg_pb = max(exec_pb, commit_pb)
    _STATE["device_result"] = round(n_blocks / piped_wall, 3)
    _emit(round(n_blocks / piped_wall, 3),
          round(serial_wall / piped_wall, 3),
          blocks=n_blocks, txs_per_block=n_txs,
          serial_wall_s=round(serial_wall, 4),
          pipelined_wall_s=round(piped_wall, 4),
          serial_blocks_per_sec=round(n_blocks / serial_wall, 3),
          exec_wall_s=round(stats["exec_wall_s"], 4),
          commit_wall_s=round(stats["commit_wall_s"], 4),
          overlap_wall_s=round(stats["overlap_wall_s"], 4),
          overlap_fraction=round(stats["overlap_fraction"], 4),
          pipeline_depth=stats["depth"],
          speculations=stats["speculations"], adopted=adopted,
          aborted=stats["aborted"], abort_reasons=stats["abort_reasons"],
          sustained_per_block_s=round(sustained_pb, 4),
          max_leg_per_block_s=round(max_leg_pb, 4),
          wall_lt_max_leg=bool(sustained_pb < max_leg_pb),
          host_cores=os.cpu_count(),
          roots_identical=True, exit_code=0)


def run_hotstate_mode():
    """RETH_TPU_BENCH_MODE=hotstate: sustained overlapping import with
    the hot-state plane (trie/hot_cache.py + the digest arena) ON vs
    OFF over the SAME block stream. The stream interleaves two sibling
    forks over one wallet set (A1 B1 A2 B2 ...), so the single-claimant
    preserved trie misses on every import and the sparse task must
    reveal its anchors each block — the exact shape the cross-block
    cache exists for. Every payload status from BOTH runs must be VALID
    (each VALID is already a computed-root == header-root check against
    the CPU truth chain) BEFORE any number prints. Headline =
    proof-target reduction factor (uncached targets/block over cached
    targets/block; the issue's bar is >= 2x). Extras carry the
    proof-fetch walls, cache hit rate, per-block H2D bytes both ways,
    the delta-upload fraction (staged rows over staged+stamped; bar
    < 0.5 on this steady overlap), and the arena epoch counters.
    Env: RETH_TPU_BENCH_HOTSTATE_BLOCKS (default 8, per fork),
    RETH_TPU_BENCH_HOTSTATE_TXS (default 24),
    RETH_TPU_BENCH_HOTSTATE_WALLETS (default 48)."""
    from reth_tpu.engine import EngineTree
    from reth_tpu.engine.tree import PayloadStatusKind
    from reth_tpu.primitives import Account
    from reth_tpu.primitives.keccak import keccak256_batch_np
    from reth_tpu.storage import MemDb, ProviderFactory
    from reth_tpu.storage.genesis import init_genesis
    from reth_tpu.testing import ChainBuilder, Wallet
    from reth_tpu.trie import TrieCommitter

    n_blocks = int(os.environ.get("RETH_TPU_BENCH_HOTSTATE_BLOCKS", "8"))
    n_txs = int(os.environ.get("RETH_TPU_BENCH_HOTSTATE_TXS", "24"))
    n_wallets = int(os.environ.get("RETH_TPU_BENCH_HOTSTATE_WALLETS", "48"))
    _STATE["metric"] = "hotstate_proof_target_reduction"
    _STATE["unit"] = "x"

    cpu = TrieCommitter(hasher=keccak256_batch_np)
    committer = TrieCommitter()  # device/jitted keccak where available
    _STATE["backend"] = getattr(committer, "backend", None) or "device"

    def make_stream():
        genesis = {Wallet(0x2000 + i).address: Account(balance=10**21)
                   for i in range(n_wallets)}
        half = n_wallets // 2
        chains = []
        for fork in range(2):
            # both forks root at the SAME genesis and churn the SAME
            # wallet set; fresh Wallet objects per fork so each chain's
            # nonce tracking starts from genesis — distinct values keep
            # the sibling headers apart
            ws = [Wallet(0x2000 + i) for i in range(n_wallets)]
            b = ChainBuilder(genesis, committer=cpu)
            for i in range(n_blocks):
                send, recv = (ws[:half], ws[half:]) if i % 2 == 0 else \
                             (ws[half:], ws[:half])
                b.build_block([send[j % half].transfer(
                    recv[j % half].address,
                    10**13 + fork * 7 + i * n_txs + j)
                    for j in range(n_txs)])
            chains.append(b)
        order = []
        for i in range(1, n_blocks + 1):
            order.append(chains[0].blocks[i])
            order.append(chains[1].blocks[i])
        return chains[0], order

    def run(hot: bool):
        b, order = make_stream()
        f = ProviderFactory(MemDb())
        init_genesis(f, b.genesis, b.accounts_at_genesis, committer=cpu)
        tree = EngineTree(f, committer=committer,
                          persistence_threshold=10**9, hot_state=hot)
        agg = {"proof_wall_s": 0.0, "proof_targets": 0,
               "cache_unblinds": 0, "h2d_bytes": 0,
               "delta_fractions": [], "sparse_blocks": 0}
        t0 = time.time()
        sts = []
        for blk in order:
            sts.append(tree.on_new_payload(blk))
            m = tree.last_sparse or {}
            if m.get("strategy") == "sparse":
                agg["sparse_blocks"] += 1
                agg["proof_wall_s"] += m.get("proof", 0.0)
                agg["proof_targets"] += m.get("proof_targets", 0)
                agg["cache_unblinds"] += m.get("cache_unblinds", 0)
                cs = m.get("commit") or {}
                agg["h2d_bytes"] += int(cs.get("h2d_bytes", 0) or 0)
                if "delta_fraction" in cs:
                    agg["delta_fractions"].append(cs["delta_fraction"])
        agg["wall_s"] = time.time() - t0
        return tree, order, sts, agg

    _STATE["phase"] = "hotstate bench: warm-up run"
    run(False)  # jit compiles + first-call allocations off the walls
    _STATE["phase"] = "hotstate bench: uncached import"
    t_cold, order, st_cold, cold = run(False)
    _STATE["phase"] = "hotstate bench: cached import"
    t_hot, _, st_hot, hot = run(True)

    _STATE["phase"] = "hotstate bench: verify roots bit-identical"
    if not all(s.status is PayloadStatusKind.VALID
               for s in st_cold + st_hot):
        _emit(0, 0, error="hotstate bench: non-VALID payload status",
              exit_code=1)
    for blk in order:
        ec = t_cold.blocks.get(blk.hash)
        eh = t_hot.blocks.get(blk.hash)
        if ec is None or eh is None or \
                ec.block.header.state_root != eh.block.header.state_root:
            _emit(0, 0, error=f"hotstate bench: cached/uncached "
                              f"divergence at block "
                              f"{blk.header.number}", exit_code=1)

    n_imported = len(order)
    cold_pb = cold["proof_targets"] / n_imported
    hot_pb = hot["proof_targets"] / n_imported
    reduction = cold_pb / hot_pb if hot_pb else float(cold_pb or 1.0)
    cache_stats = t_hot.hot_cache.stats() if t_hot.hot_cache else {}
    lookups = cache_stats.get("hits", 0) + cache_stats.get("misses", 0)
    hit_rate = cache_stats.get("hits", 0) / lookups if lookups else 0.0
    arena = t_hot.hot_arena.snapshot() if t_hot.hot_arena else {}
    dfs = hot["delta_fractions"]
    _STATE["device_result"] = round(reduction, 3)
    _emit(round(reduction, 3), round(reduction, 3),
          blocks=n_imported, txs_per_block=n_txs,
          uncached_wall_s=round(cold["wall_s"], 4),
          cached_wall_s=round(hot["wall_s"], 4),
          uncached_proof_wall_s=round(cold["proof_wall_s"], 4),
          cached_proof_wall_s=round(hot["proof_wall_s"], 4),
          uncached_proof_targets_per_block=round(cold_pb, 2),
          cached_proof_targets_per_block=round(hot_pb, 2),
          cache_unblinds=hot["cache_unblinds"],
          cache_hit_rate=round(hit_rate, 4),
          cache_entries=cache_stats.get("entries", 0),
          cache_stale_drops=cache_stats.get("stale_drops", 0),
          uncached_h2d_bytes_per_block=round(
              cold["h2d_bytes"] / n_imported),
          cached_h2d_bytes_per_block=round(
              hot["h2d_bytes"] / n_imported),
          delta_upload_fraction=round(sum(dfs) / len(dfs), 4)
          if dfs else None,
          arena_delta_epochs=arena.get("delta_epochs", 0),
          arena_full_epochs=arena.get("full_epochs", 0),
          arena_resident_rows=arena.get("resident_rows", 0),
          arena_evictions=arena.get("evictions", 0),
          arena_faults=arena.get("faults", 0),
          sparse_blocks=hot["sparse_blocks"],
          roots_identical=True, exit_code=0)


def _mesh_inner(n: int) -> None:
    """Inner body of ``RETH_TPU_BENCH_MODE=mesh``: runs in a subprocess
    whose XLA host-device count is forced to ``n``, commits the SAME
    synthetic update stream through the single-device committer and the
    mesh-sharded one (FusedMeshEngine over a ``parallel/mesh.py``
    HashMesh — the production turbo level loop, not a demo reduction),
    asserts the roots bit-identical, and prints ONE raw JSON line with
    the mesh throughput + compile/steady wall split."""
    from reth_tpu.metrics import compile_tracker
    from reth_tpu.parallel.mesh import HashMesh
    from reth_tpu.trie.turbo import TurboCommitter

    accounts = int(os.environ.get("RETH_TPU_BENCH_MESH_ACCOUNTS", "20000"))
    slots = int(os.environ.get("RETH_TPU_BENCH_MESH_SLOTS",
                               str(max(accounts * 2 // 5, 100))))
    tier = int(os.environ.get("RETH_TPU_BENCH_MESH_TIER", "4096"))
    _STATE["phase"] = f"mesh inner ({n} devices): state build"
    storage_jobs, account_jobs = build_state(accounts, slots)

    single = TurboCommitter(backend="device", min_tier=tier)
    _STATE["phase"] = f"mesh inner ({n} devices): single-device warm pass"
    run_rebuild(single, storage_jobs, account_jobs, pipelined=True)
    _STATE["phase"] = f"mesh inner ({n} devices): single-device run"
    roots_single, _h, dt_single = run_rebuild(
        single, storage_jobs, account_jobs, pipelined=True)

    hash_mesh = HashMesh.build(n)
    meshc = TurboCommitter(backend="device", min_tier=tier, mesh=hash_mesh)
    compile_before = _compile_split()["compile_wall_s"]
    _STATE["phase"] = f"mesh inner ({n} devices): mesh warm pass (compiles)"
    run_rebuild(meshc, storage_jobs, account_jobs, pipelined=True)
    compile_wall = round(
        _compile_split()["compile_wall_s"] - compile_before, 4)
    _STATE["phase"] = f"mesh inner ({n} devices): mesh measured pass"
    roots_mesh, hashed, dt_mesh = run_rebuild(
        meshc, storage_jobs, account_jobs, pipelined=True)

    ok = roots_mesh == roots_single
    print(json.dumps({
        "n_devices": hash_mesh.n_devices,
        "roots_identical": ok,
        "hashes_per_sec": round(hashed / dt_mesh, 1),
        "steady_wall_s": round(dt_mesh, 4),
        "compile_wall_s": compile_wall,
        "single_hashes_per_sec": round(hashed / dt_single, 1),
        "hashed": hashed,
        "mesh_degraded": hash_mesh.snapshot()["unhealthy"],
        "compiled_shapes": compile_tracker.totals()["shapes"],
    }), flush=True)
    os._exit(0 if ok else 4)


def run_mesh_mode() -> None:
    """RETH_TPU_BENCH_MODE=mesh: the production turbo/fused rebuild loop
    SPMD-sharded over 1/2/4/8 SIMULATED host devices — each mesh size in
    its own subprocess (the XLA host-device count is fixed at backend
    init), with ``JAX_PLATFORMS=cpu`` forced and the axon plugin scrubbed
    so the mode is hermetic (it measures sharding overhead/scaling shape,
    never the tunnel). Roots are verified bit-identical to the
    single-device committer on the same update stream BEFORE any number
    prints; the headline is the largest mesh's steady-state hashes/s with
    per-mesh-size throughput + compile wall in ``per_mesh``. Env:
    RETH_TPU_BENCH_MESH_DEVICES (default "1,2,4,8"),
    RETH_TPU_BENCH_MESH_ACCOUNTS / _SLOTS / _TIER (workload)."""
    import subprocess

    sizes = sorted({int(x) for x in os.environ.get(
        "RETH_TPU_BENCH_MESH_DEVICES", "1,2,4,8").split(",") if x.strip()})
    _STATE["metric"] = "mesh_rebuild_hashes_per_sec"
    # simulated host devices: honest labeling — this mode never touches
    # the device tunnel, it measures the sharded data plane's scaling
    _STATE["backend"] = "jax-cpu-mesh"
    per: dict[str, dict] = {}
    degraded = 0
    budget = max(90, (_DEADLINE - 60) // max(len(sizes), 1))
    for n in sizes:
        _STATE["phase"] = f"mesh subprocess ({n} devices)"
        env = {k: v for k, v in os.environ.items()
               if k not in ("PALLAS_AXON_POOL_IPS", "RETH_TPU_WARMUP")}
        env["JAX_PLATFORMS"] = "cpu"
        flags = " ".join(f for f in env.get("XLA_FLAGS", "").split()
                         if "host_platform_device_count" not in f)
        env["XLA_FLAGS"] = \
            (flags + f" --xla_force_host_platform_device_count={n}").strip()
        env["RETH_TPU_BENCH_MESH_INNER"] = str(n)
        env["RETH_TPU_BENCH_TIMEOUT"] = str(budget)
        try:
            r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                               env=env, capture_output=True, text=True,
                               timeout=budget + 60)
        except subprocess.TimeoutExpired:
            _emit(0, 0, error=f"mesh inner ({n} devices) exceeded "
                              f"{budget + 60}s", exit_code=0)
        line = None
        for out_line in reversed(r.stdout.strip().splitlines()):
            try:
                parsed = json.loads(out_line)
            except ValueError:
                continue
            if isinstance(parsed, dict):
                line = parsed
                break
        if not line or "n_devices" not in line or line.get("error"):
            diag = ((line or {}).get("error")
                    or (r.stderr or r.stdout or "no output")[-300:])
            _emit(0, 0, error=f"mesh inner ({n} devices) failed "
                              f"rc={r.returncode}: {diag}", exit_code=0)
        if not line.get("roots_identical"):
            # acceptance contract: a root divergence is a correctness
            # failure — no throughput number may print over it
            _emit(0, 0, error=f"mesh inner ({n} devices): roots diverged "
                              f"from the single-device committer",
                  exit_code=1)
        degraded = max(degraded, int(line.get("mesh_degraded", 0)))
        per[str(line["n_devices"])] = {
            k: line[k] for k in ("hashes_per_sec", "compile_wall_s",
                                 "steady_wall_s", "single_hashes_per_sec",
                                 "hashed", "compiled_shapes")
            if k in line}
    top = per[str(max(sizes))]
    base = per.get("1", {}).get("hashes_per_sec")
    _STATE["device_result"] = top["hashes_per_sec"]
    _emit(top["hashes_per_sec"],
          round(top["hashes_per_sec"] / base, 3) if base else 0,
          n_devices=max(sizes), per_mesh=per, mesh_degraded=degraded,
          roots_identical=True, exit_code=0)


def _subtrie_inner(n: int) -> None:
    """Inner body of ``RETH_TPU_BENCH_MODE=subtrie``: runs in a subprocess
    whose XLA host-device count is forced to ``n``, commits the SAME
    window set through the per-level committer (Mega/FusedMesh — one
    dispatch per staged level) and the whole-subtrie committer at
    k ∈ {1,2,4,8}, asserts every k's roots bit-identical to the
    per-level path BEFORE any number prints, and emits ONE raw JSON line
    with wall + dispatches/block per k."""
    from reth_tpu.metrics import fused_metrics
    from reth_tpu.parallel.mesh import HashMesh
    from reth_tpu.trie.turbo import TurboCommitter

    accounts = int(os.environ.get("RETH_TPU_BENCH_SUBTRIE_ACCOUNTS", "8000"))
    slots = int(os.environ.get("RETH_TPU_BENCH_SUBTRIE_SLOTS",
                               str(max(accounts * 2 // 5, 100))))
    tier = int(os.environ.get("RETH_TPU_BENCH_SUBTRIE_TIER", "1024"))
    ks = [int(x) for x in os.environ.get(
        "RETH_TPU_BENCH_SUBTRIE_KS", "1,2,4,8").split(",") if x.strip()]
    _STATE["phase"] = f"subtrie inner ({n} devices): state build"
    storage_jobs, account_jobs = build_state(accounts, slots)
    mesh = HashMesh.build(n) if n > 1 else None

    def measure(k: int):
        c = TurboCommitter(backend="device", min_tier=tier, mesh=mesh,
                           subtrie_levels=k)
        _STATE["phase"] = f"subtrie inner ({n} dev, k={k}): warm pass"
        run_rebuild(c, storage_jobs, account_jobs, pipelined=True)
        d0 = fused_metrics.dispatches_cum
        _STATE["phase"] = f"subtrie inner ({n} dev, k={k}): measured pass"
        roots, hashed, dt = run_rebuild(c, storage_jobs, account_jobs,
                                        pipelined=True)
        # one rebuild pass = 2 committer runs (storage tries + account
        # prefix subtries) — the "block" unit for dispatches/block
        disp = fused_metrics.dispatches_cum - d0
        return roots, hashed, dt, disp, round(disp / 2, 1)

    roots_pl, hashed, dt_pl, disp_pl, dpb_pl = measure(0)
    per_k: dict[str, dict] = {}
    ok = True
    for k in ks:
        roots_k, _h, dt_k, disp_k, dpb_k = measure(k)
        if roots_k != roots_pl:
            ok = False
        per_k[str(k)] = {
            "wall_s": round(dt_k, 4),
            "dispatches": disp_k,
            "dispatches_per_block": dpb_k,
            "dispatch_reduction": round(disp_pl / disp_k, 2) if disp_k else 0,
            "hashes_per_sec": round(hashed / dt_k, 1),
        }
    print(json.dumps({
        "n_devices": n,
        "roots_identical": ok,
        "hashed": hashed,
        "perlevel": {"wall_s": round(dt_pl, 4), "dispatches": disp_pl,
                     "dispatches_per_block": dpb_pl,
                     "hashes_per_sec": round(hashed / dt_pl, 1)},
        "per_k": per_k,
    }), flush=True)
    os._exit(0 if ok else 4)


def run_subtrie_mode() -> None:
    """RETH_TPU_BENCH_MODE=subtrie: whole-subtrie k-level fused commits
    vs the per-level committer — dispatches/block + wall at
    k ∈ {1,2,4,8}, on 1/2/4/8 SIMULATED host devices (one hermetic
    subprocess per mesh size, JAX_PLATFORMS=cpu forced, axon plugin
    scrubbed). Every k's roots are verified bit-identical to the
    per-level committer on the same window set BEFORE any number prints;
    the headline is the dispatch-count reduction at the largest k on the
    largest mesh. Env: RETH_TPU_BENCH_SUBTRIE_DEVICES (default
    "1,2,4,8"), RETH_TPU_BENCH_SUBTRIE_KS (default "1,2,4,8"),
    RETH_TPU_BENCH_SUBTRIE_ACCOUNTS / _SLOTS / _TIER (workload)."""
    import subprocess

    sizes = sorted({int(x) for x in os.environ.get(
        "RETH_TPU_BENCH_SUBTRIE_DEVICES", "1,2,4,8").split(",") if x.strip()})
    _STATE["metric"] = "subtrie_dispatch_reduction"
    _STATE["unit"] = "x"
    _STATE["backend"] = "jax-cpu-mesh"
    per: dict[str, dict] = {}
    budget = max(120, (_DEADLINE - 60) // max(len(sizes), 1))
    for n in sizes:
        _STATE["phase"] = f"subtrie subprocess ({n} devices)"
        env = {k: v for k, v in os.environ.items()
               if k not in ("PALLAS_AXON_POOL_IPS", "RETH_TPU_WARMUP")}
        env["JAX_PLATFORMS"] = "cpu"
        flags = " ".join(f for f in env.get("XLA_FLAGS", "").split()
                         if "host_platform_device_count" not in f)
        env["XLA_FLAGS"] = \
            (flags + f" --xla_force_host_platform_device_count={n}").strip()
        env["RETH_TPU_BENCH_SUBTRIE_INNER"] = str(n)
        env["RETH_TPU_BENCH_TIMEOUT"] = str(budget)
        try:
            r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                               env=env, capture_output=True, text=True,
                               timeout=budget + 60)
        except subprocess.TimeoutExpired:
            _emit(0, 0, error=f"subtrie inner ({n} devices) exceeded "
                              f"{budget + 60}s", exit_code=0)
        line = None
        for out_line in reversed(r.stdout.strip().splitlines()):
            try:
                parsed = json.loads(out_line)
            except ValueError:
                continue
            if isinstance(parsed, dict):
                line = parsed
                break
        if not line or "n_devices" not in line or line.get("error"):
            diag = ((line or {}).get("error")
                    or (r.stderr or r.stdout or "no output")[-300:])
            _emit(0, 0, error=f"subtrie inner ({n} devices) failed "
                              f"rc={r.returncode}: {diag}", exit_code=0)
        if not line.get("roots_identical"):
            # acceptance contract: a root divergence is a correctness
            # failure — no dispatch number may print over it
            _emit(0, 0, error=f"subtrie inner ({n} devices): k-level roots "
                              f"diverged from the per-level committer",
                  exit_code=1)
        per[str(line["n_devices"])] = {
            "perlevel": line["perlevel"], "per_k": line["per_k"],
            "hashed": line["hashed"]}
    top = per[str(max(sizes))]
    best_k = max(top["per_k"], key=int)
    headline = top["per_k"][best_k]["dispatch_reduction"]
    _STATE["device_result"] = headline
    _emit(headline, headline,
          n_devices=max(sizes), k=int(best_k),
          dispatches_per_block=top["per_k"][best_k]["dispatches_per_block"],
          perlevel_dispatches_per_block=top["perlevel"][
              "dispatches_per_block"],
          per_mesh=per, roots_identical=True,
          verified="k-level roots bit-identical to the per-level "
                   "committer at every mesh size before measuring",
          exit_code=0)


def run_fleet_mode() -> None:
    """RETH_TPU_BENCH_MODE=fleet: sustained RPC throughput + p99 through
    the fleet gateway at 1/2/4/8 replicas vs the single-node gateway
    (fleet/): a dev full node in fleet mode feeds witness-validated
    replica SUBPROCESSES over the socket protocol, and the load runs two
    mixes through the gateway — duplicate-heavy (a small pool of hot
    reads: trackers/wallets hammering the same few calls, where the
    gateway cache + the ring's stable key→replica mapping should absorb
    nearly everything) and long-tail (mostly-distinct eth_calls, where
    replicas absorb the execution work the full node would otherwise
    serialize under its handler lock). Before ANY number prints, every
    distinct request's fleet-routed response is verified bit-identical
    to a direct ungated dispatch on the full node. Env:
    RETH_TPU_BENCH_FLEET_SIZES (default "1,2,4,8"),
    RETH_TPU_BENCH_FLEET_CLIENTS (default 6),
    RETH_TPU_BENCH_FLEET_REQS (requests/client/mix, default 50),
    RETH_TPU_BENCH_FLEET_KEYS (duplicate pool size, default 8)."""
    import shutil
    import subprocess
    import tempfile
    from pathlib import Path

    from reth_tpu.node import Node, NodeConfig
    from reth_tpu.primitives.keccak import keccak256_batch_np
    from reth_tpu.primitives.types import Account
    from reth_tpu.rpc.server import RpcServer
    from reth_tpu.testing import ChainBuilder, Wallet
    from reth_tpu.trie.committer import TrieCommitter

    sizes = [int(s) for s in os.environ.get(
        "RETH_TPU_BENCH_FLEET_SIZES", "1,2,4,8").split(",") if s]
    clients = int(os.environ.get("RETH_TPU_BENCH_FLEET_CLIENTS", "6"))
    reqs = int(os.environ.get("RETH_TPU_BENCH_FLEET_REQS", "50"))
    n_keys = int(os.environ.get("RETH_TPU_BENCH_FLEET_KEYS", "8"))
    _STATE["metric"] = "fleet_requests_per_sec"
    _STATE["unit"] = "requests/s"
    _STATE["backend"] = "cpu"
    _STATE["phase"] = "fleet node build"

    # fleet observability coverage: the bench runs TRACED — the node and
    # every replica export Chrome traces, and trace_stitched on the JSON
    # line asserts cross-process parent-id resolution held during the
    # bench. The exporter must install BEFORE the node mines: bench
    # main() enables span recording at process start (error-trail
    # contract), so witness spans generated during mining would
    # otherwise record + propagate without ever exporting.
    from reth_tpu import tracing as _tracing

    base = Path(tempfile.mkdtemp(prefix="reth-tpu-bench-fleet-"))
    _tracing.init_block_tracing(chrome_path=base / "node.trace.json")
    trace_stitched = False
    trace_pids = 0
    trace_diag: dict = {}

    committer = TrieCommitter(hasher=keccak256_batch_np)
    committer.turbo_backend = "numpy"
    wallet = Wallet(0xA11CE)
    builder = ChainBuilder({wallet.address: Account(balance=10**21)},
                           committer=committer)
    node = Node(NodeConfig(dev=True, genesis_header=builder.genesis,
                           genesis_alloc=builder.accounts_at_genesis,
                           fleet=True, http_port=0, authrpc_port=0),
                committer=committer)
    node.start_rpc()
    node.fleet_router.probe_interval = 0  # probed explicitly below
    fport = node.feed_server.port
    sink = b"\x0b" * 20
    blocks = 3
    for i in range(blocks):
        node.pool.add_transaction(wallet.transfer(sink, 100 + i))
        node.miner.mine_block(timestamp=1_700_000_000 + i * 12)

    def call_body(i):
        return json.dumps({
            "jsonrpc": "2.0", "id": 1, "method": "eth_call",
            "params": [{"from": "0x" + wallet.address.hex(),
                        "to": "0x" + sink.hex(), "value": hex(i)},
                       "latest"]}).encode()

    dup_pool = [call_body(i) for i in range(n_keys - 2)]
    dup_pool.append(json.dumps({
        "jsonrpc": "2.0", "id": 1, "method": "eth_getBlockByNumber",
        "params": [hex(blocks), False]}).encode())
    dup_pool.append(json.dumps({
        "jsonrpc": "2.0", "id": 1, "method": "eth_getLogs",
        "params": [{"fromBlock": "0x1", "toBlock": hex(blocks)}]}).encode())
    tail_pool = [call_body(1000 + i) for i in range(clients * reqs)]

    def run_mix(pool, duplicate: bool):
        """(requests/s, p99_ms) over `clients` threads; duplicate mix
        samples a hot pool, long-tail walks distinct requests."""
        lats: list[float] = []
        errs: list = []
        lock = threading.Lock()

        def worker(c):
            rng = np.random.default_rng(c)
            try:
                for i in range(reqs):
                    body = (pool[int(rng.integers(0, len(pool)))]
                            if duplicate else pool[c * reqs + i])
                    t0 = time.monotonic()
                    resp = json.loads(node.rpc.handle(body))
                    dt = time.monotonic() - t0
                    with lock:
                        lats.append(dt)
                        if "error" in resp:
                            errs.append(resp["error"])
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errs.append(e)

        ts = [threading.Thread(target=worker, args=(c,))
              for c in range(clients)]
        t0 = time.time()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        wall = time.time() - t0
        if errs:
            raise RuntimeError(f"fleet bench request failed: {errs[0]}")
        return (round(len(lats) / wall, 1),
                round(float(np.percentile(lats, 99)) * 1e3, 2))

    procs: list = []
    urls: list[str] = []
    per_fleet: dict = {}
    try:
        _STATE["phase"] = "replica spawn"
        env = {k: v for k, v in os.environ.items()
               if not k.startswith("RETH_TPU_FAULT_")}
        env["JAX_PLATFORMS"] = "cpu"
        port_files = []
        for i in range(max(sizes)):
            pf = base / f"replica-{i}.port"
            log = open(base / f"replica-{i}.log", "w")
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "reth_tpu.fleet", "replica",
                 "--feed", f"127.0.0.1:{fport}",
                 "--port-file", str(pf), "--id", f"bench-r{i}",
                 "--trace-file", str(base / f"replica-{i}.trace.json")],
                env=env, stdout=log, stderr=log))
            port_files.append(pf)
        deadline = time.time() + 90
        for pf in port_files:
            while not pf.exists() and time.time() < deadline:
                time.sleep(0.05)
            if not pf.exists():
                _emit(0, 0, error="replica subprocess never bound its "
                                  "port", exit_code=1)
            urls.append("http://127.0.0.1:"
                        f"{json.loads(pf.read_text())['http_port']}")

        # single-node baseline: the same gateway with an empty ring
        _STATE["phase"] = "single-node baseline"
        node.gateway.on_head_change()  # comparable cold cache per run
        single = dict(zip(("dup_rps", "dup_p99_ms"),
                          run_mix(dup_pool, duplicate=True)))
        single.update(zip(("tail_rps", "tail_p99_ms"),
                          run_mix(tail_pool, duplicate=False)))

        naked = RpcServer(lock=node.rpc.lock)
        naked.methods = node.rpc.methods
        router = node.fleet_router
        for n in sizes:
            _STATE["phase"] = f"fleet x{n}: sync + verify"
            for url in urls[:n]:
                router.register(url)
            for url in urls[n:]:
                for h in list(router.replicas.values()):
                    if h.url == url:
                        router.deregister(h.id)
            deadline = time.time() + 60
            while time.time() < deadline:
                router.probe_once()
                s = router.snapshot()
                if s["healthy"] == n and s["max_lag"] == 0:
                    break
                time.sleep(0.1)
            else:
                _emit(0, 0, error=f"fleet x{n} never converged: "
                                  f"{router.snapshot()}", exit_code=1)
            # bit-identical BEFORE any number prints: every distinct
            # request through the fleet vs a direct ungated dispatch
            node.gateway.on_head_change()
            for body in dup_pool + tail_pool[::17]:
                via_fleet = json.loads(node.rpc.handle(body))
                direct = json.loads(naked.handle(body))
                if via_fleet != direct:
                    _emit(0, 0, error=f"fleet x{n} response mismatch: "
                                      f"{body[:120]!r}", exit_code=1)
            _STATE["phase"] = f"fleet x{n}: measured run"
            node.gateway.on_head_change()
            r0 = router.snapshot()
            entry = dict(zip(("dup_rps", "dup_p99_ms"),
                             run_mix(dup_pool, duplicate=True)))
            entry.update(zip(("tail_rps", "tail_p99_ms"),
                             run_mix(tail_pool, duplicate=False)))
            r1 = router.snapshot()
            entry["routed"] = r1["routed"] - r0["routed"]
            entry["failovers"] = r1["failovers"] - r0["failovers"]
            entry["local"] = (r1["local_fallbacks"]
                              - r0["local_fallbacks"])
            # per-replica breakdown: routed reads this run (router
            # handles) + lifetime served/read-p99 pulled over the
            # metrics federation — a hot or slow replica shows on the
            # bench line, not just in its own process
            before = {r["id"]: r["routed"] for r in r0["replicas"]}
            node.fleet_federation.pull_once()
            per_replica = {}
            for r in r1["replicas"]:
                rid = r["id"]
                served = node.fleet_federation.replica_latest(
                    rid, "gateway_requests_total_read")
                p99 = node.fleet_federation.replica_quantile(
                    rid, "gateway_service_seconds_read", 0.99)
                per_replica[rid] = {
                    "routed": r["routed"] - before.get(rid, 0),
                    "served_reads": (served["v"] if served else None),
                    "read_p99_ms": (round(p99 * 1e3, 3)
                                    if p99 is not None else None),
                }
            entry["per_replica"] = per_replica
            per_fleet[n] = entry
        # stitched-trace assertion: a few more routed reads, then merge
        # the node's + every replica's Chrome trace — every
        # cross-process parent id must resolve
        _STATE["phase"] = "trace stitch check"
        node.gateway.on_head_change()
        for i in range(8):
            node.rpc.handle(call_body(31000 + i))
        stitch = _tracing.stitch_chrome_traces(
            [base / "node.trace.json",
             *sorted(base.glob("replica-*.trace.json"))])
        trace_pids = len(stitch["pids"])
        trace_stitched = bool(stitch["stitched"]
                              and trace_pids >= min(max(sizes), 2) + 1)
        trace_diag = {"cross_refs": stitch["cross_refs"],
                      "unresolved_cross":
                          len(set(stitch["unresolved_cross"]))}
        if os.environ.get("RETH_TPU_BENCH_TRACE_DEBUG"):
            # triage aid: print the events whose cross-process parent
            # never resolved (which span, which pid, which parent)
            bad = set(stitch["unresolved_cross"])
            for e in stitch["events"]:
                if (e.get("args") or {}).get("parent_id") in bad:
                    sys.stderr.write(f"UNRESOLVED {json.dumps(e)}\n")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        shutil.rmtree(base, ignore_errors=True)
        node.stop()
        _tracing.shutdown_block_tracing()

    top = per_fleet[max(sizes)]
    value = top["tail_rps"]
    _STATE["device_result"] = value
    lo = per_fleet[min(sizes)]["tail_rps"]
    _emit(value,
          round(value / single["tail_rps"], 3) if single["tail_rps"] else 0,
          per_fleet={str(k): v for k, v in per_fleet.items()},
          single_node=single, fleet_sizes=sizes,
          # the scaling shape is the honest headline on a small host: a
          # 1-core container pays the HTTP hop on every routed read, so
          # vs_baseline < 1 there while fleet_scaling still shows the
          # fan-out working (replicas are real processes)
          fleet_scaling=round(value / lo, 2) if lo else 0,
          requests_per_mix=clients * reqs, duplicate_pool=len(dup_pool),
          trace_stitched=trace_stitched, trace_pids=trace_pids,
          trace_diag=trace_diag,
          verified="bit-identical vs ungated dispatch before measuring",
          exit_code=0)


def run_ha_mode() -> None:
    """RETH_TPU_BENCH_MODE=ha: leader-kill failover wall through the HA
    pair (fleet/standby.py). A leader subprocess (fleet+WAL dev node,
    mining continuously) ships its durable stream to a hot-standby
    subprocess; two replica subprocesses serve reads with the standby's
    takeover feed as their failover endpoint. A continuous read load
    runs against the replicas while the leader is SIGKILLed mid-stream;
    the headline is ``promote_ms`` (the standby's catching-up → leading
    wall) with ``failover_wall_s`` (kill → promoted gateway serving)
    and ``reads_failed`` (read-load failures across the whole failover
    window — the HA promise is zero). Env:
    RETH_TPU_BENCH_HA_HEARTBEAT (detection timeout, default 1.0s),
    RETH_TPU_BENCH_HA_BLOCKS (blocks mined before the kill, default 6)."""
    import shutil
    import signal as signal_mod
    import socket as socket_mod
    import subprocess
    import tempfile
    import urllib.request
    from pathlib import Path

    from reth_tpu.chaos import _child_env, _read_record

    heartbeat = float(os.environ.get("RETH_TPU_BENCH_HA_HEARTBEAT", "1.0"))
    pre_blocks = int(os.environ.get("RETH_TPU_BENCH_HA_BLOCKS", "6"))
    _STATE["metric"] = "ha_promote_ms"
    _STATE["unit"] = "ms"
    _STATE["backend"] = "cpu"
    _STATE["phase"] = "ha pair spawn"
    base = Path(tempfile.mkdtemp(prefix="reth-tpu-bench-ha-"))
    procs: list = []

    def rpc(port, method, params=None, timeout=10.0):
        body = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                           "params": params or []}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/", data=body,
            headers={"Content-Type": "application/json"})
        return json.loads(urllib.request.urlopen(req, timeout=timeout).read())

    def spawn(cmd, env, log_name):
        log = open(base / log_name, "w")
        p = subprocess.Popen(cmd, env=env, stdout=log, stderr=log)
        procs.append(p)
        return p

    def wait_port_file(pf, what, deadline_s=90):
        deadline = time.time() + deadline_s
        while not pf.exists() and time.time() < deadline:
            time.sleep(0.05)
        if not pf.exists():
            _emit(0, 0, error=f"{what} never bound its port", exit_code=1)
        return json.loads(pf.read_text())

    try:
        with socket_mod.socket() as s:
            s.bind(("127.0.0.1", 0))
            tport = s.getsockname()[1]
        leader_dir = base / "leader"
        lpf = base / "leader.port"
        leader = spawn(
            [sys.executable, "-m", "reth_tpu.chaos", "ha-leader",
             "--datadir", str(leader_dir), "--seed", "1",
             "--port-file", str(lpf)],
            _child_env(), "leader.log")
        lports = wait_port_file(lpf, "leader")
        lhttp, lfeed = lports["http_port"], lports["feed_port"]

        spf = base / "standby.port"
        spawn(
            [sys.executable, "-m", "reth_tpu.fleet", "standby",
             "--feed", f"127.0.0.1:{lfeed}",
             "--datadir", str(base / "standby"),
             "--takeover-feed-port", str(tport),
             "--heartbeat-timeout", str(heartbeat),
             "--id", "bench-sb", "--port-file", str(spf)],
            _child_env(), "standby.log")
        shttp = wait_port_file(spf, "standby")["http_port"]

        rports = []
        for i in range(2):
            rpf = base / f"replica-{i}.port"
            spawn(
                [sys.executable, "-m", "reth_tpu.fleet", "replica",
                 "--feed", f"127.0.0.1:{lfeed}",
                 "--failover-feed", f"127.0.0.1:{tport}",
                 "--auto-register",
                 "--register", f"http://127.0.0.1:{lhttp}",
                 "--id", f"bench-r{i}", "--port-file", str(rpf)],
                _child_env(), f"replica-{i}.log")
            rports.append(wait_port_file(rpf, f"replica {i}")["http_port"])

        # gate: a recorded chain + a caught-up standby + serving replicas
        _STATE["phase"] = "ha pair sync"
        deadline = time.time() + 120
        status: dict = {}
        while time.time() < deadline:
            mined = [l for l in _read_record(leader_dir) if "hash" in l]
            try:
                status = rpc(shttp, "fleet_standbyStatus")["result"]
            except Exception:  # noqa: BLE001 — standby still booting
                status = {}
            if (len(mined) >= pre_blocks
                    and status.get("records_applied", 0) > 0
                    and not status.get("awaiting_resync", True)
                    and status.get("lag_heads", 99) <= 2):
                break
            time.sleep(0.1)
        else:
            _emit(0, 0, error=f"standby never caught up: "
                              f"{json.dumps(status)[:300]}", exit_code=1)

        # continuous read load against the replicas across the failover
        _STATE["phase"] = "leader kill + failover"
        stop = threading.Event()
        reads = {"total": 0, "failed": 0}
        rlock = threading.Lock()

        def load():
            i = 0
            while not stop.is_set():
                port = rports[i % len(rports)]
                i += 1
                try:
                    resp = rpc(port, "eth_getBlockByNumber",
                               ["latest", False], timeout=5)
                    bad = "error" in resp
                except Exception:  # noqa: BLE001 — transport loss counts
                    bad = True
                with rlock:
                    reads["total"] += 1
                    reads["failed"] += 1 if bad else 0
                time.sleep(0.01)

        loaders = [threading.Thread(target=load, daemon=True)
                   for _ in range(2)]
        for t in loaders:
            t.start()
        time.sleep(0.5)
        os.kill(leader.pid, signal_mod.SIGKILL)
        leader.wait()
        killed_at = time.time()

        deadline = time.time() + 120
        while time.time() < deadline:
            try:
                status = rpc(shttp, "fleet_standbyStatus")["result"]
            except Exception:  # noqa: BLE001 — admin RPC mid-promotion
                status = {}
            if status.get("state") in ("leading", "failed"):
                break
            time.sleep(0.05)
        if status.get("state") != "leading":
            _emit(0, 0, error=f"standby never promoted: "
                              f"{json.dumps(status, default=str)[:300]}",
                  exit_code=1)
        pnode = status["node"]
        failover_wall_s = time.time() - killed_at

        # the promoted gateway serves, and the replicas re-anchor on it
        _STATE["phase"] = "post-promotion re-anchor"
        promoted_reads_failed = 0
        for i in range(8):
            try:
                resp = rpc(pnode["http_port"], "eth_blockNumber")
                promoted_reads_failed += 1 if "error" in resp else 0
            except Exception:  # noqa: BLE001
                promoted_reads_failed += 1
        deadline = time.time() + 90
        reanchored = False
        while time.time() < deadline and not reanchored:
            try:
                fs = rpc(pnode["http_port"], "fleet_status")["result"]
                reanchored = fs.get("registered", 0) >= 2
            except Exception:  # noqa: BLE001
                pass
            if not reanchored:
                time.sleep(0.2)
        stop.set()
        for t in loaders:
            t.join(timeout=5)

        value = float(status.get("promote_ms") or 0.0)
        _STATE["device_result"] = value
        _emit(value, 1.0,
              reads_failed=reads["failed"], reads_total=reads["total"],
              promoted_reads_failed=promoted_reads_failed,
              failover_wall_s=round(failover_wall_s, 2),
              detection_timeout_s=heartbeat,
              replicas_reanchored=reanchored,
              leader_epoch=status.get("leader_epoch"),
              standby_resyncs=status.get("resyncs_applied"),
              records_applied=status.get("records_applied"),
              verified="promoted head root recomputed at takeover "
                       "(recovery_verify_root)",
              exit_code=0 if (reads["failed"] == 0
                              and promoted_reads_failed == 0
                              and reanchored) else 1)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        shutil.rmtree(base, ignore_errors=True)


def _txflow_schedule(wallets, under_wallet, txs_per_wallet: int, rng,
                     value_tag: int):
    """One adversarial submission schedule: per-wallet nonce chains with
    duplicates, valid replacements (2x fees, >= the 10% bump), underpriced
    replacements (+5%, below the bump), and one dedicated underpriced tx
    (fee cap below the base fee — admitted, never executable). Returns
    ``(schedule, slots)`` where schedule entries are ``(kind, tx, track)``
    in submission order (per-sender order preserved by a round-robin
    interleave) and ``slots`` is the number of (sender, nonce) slots the
    chain-valid stream should eventually mine exactly once each."""
    from itertools import zip_longest

    from reth_tpu.primitives.types import Transaction

    sink = b"\x0f" * 20
    per_wallet = []
    for wi, w in enumerate(wallets):
        seq = []
        bases = []
        for k in range(txs_per_wallet):
            tx = w.transfer(sink, 10**9 + value_tag + wi * 1000 + k)
            bases.append(tx)
            seq.append(("base", tx, True))
        # duplicate: the same raw tx again — rejected "already known"
        seq.append(("dup", bases[int(rng.integers(0, len(bases)))], False))
        if wi % 3 == 0:
            # valid replacement: same nonce at 2x fees — the winner; the
            # base it replaces must NEVER be mined (asserted via slots)
            tgt = bases[int(rng.integers(0, len(bases)))]
            seq.append(("repl", w.sign_tx(Transaction(
                tx_type=2, chain_id=1, nonce=tgt.nonce,
                max_fee_per_gas=tgt.max_fee_per_gas * 2,
                max_priority_fee_per_gas=tgt.max_priority_fee_per_gas * 2,
                gas_limit=21_000, to=sink, value=tgt.value + 1,
            ), bump_nonce=False), True))
        elif wi % 3 == 1:
            # underpriced replacement: +5% < the 10% min bump — rejected
            # ("replacement underpriced", or "nonce too low" when the base
            # won the race to a block first; both are correct outcomes)
            tgt = bases[int(rng.integers(0, len(bases)))]
            seq.append(("repl_under", w.sign_tx(Transaction(
                tx_type=2, chain_id=1, nonce=tgt.nonce,
                max_fee_per_gas=tgt.max_fee_per_gas * 105 // 100,
                max_priority_fee_per_gas=tgt.max_priority_fee_per_gas,
                gas_limit=21_000, to=sink, value=tgt.value + 1,
            ), bump_nonce=False), False))
        per_wallet.append(seq)
    sched = [e for rnd in zip_longest(*per_wallet) for e in rnd
             if e is not None]
    # fee cap below any base fee: admitted (balance/nonce are fine) but
    # effective tip < 0 — sits in the basefee bucket, never selected
    sched.insert(int(rng.integers(0, len(sched) + 1)),
                 ("under", under_wallet.transfer(
                     sink, 1, max_fee_per_gas=1,
                     max_priority_fee_per_gas=0), False))
    return sched, len(wallets) * txs_per_wallet


def _txflow_verify(node) -> str | None:
    """The txflow acceptance contract: wait for the hot candidate to reach
    pool parity, then compare its inclusion set bit-identically against ONE
    serial greedy ``build_payload`` pass over a CLONED pool (same txs,
    submission order preserved so heap ties break identically; the clone
    absorbs the serial pass's evictions instead of the live pool). Returns
    None on bit-identity, else a diagnostic string. Mining must be paused
    by the caller — the comparison needs a quiescent head."""
    from reth_tpu.payload.builder import build_payload
    from reth_tpu.pool.pool import TransactionPool

    prod = node.producer
    got = parent = attrs = None
    deadline = time.time() + 20
    while time.time() < deadline:
        with prod._lock:
            cand = prod.candidate
            with node.pool._lock:
                if (cand is not None and cand.window is None
                        and cand.parent_hash == node.tree.head_hash
                        and cand.pool_seq == node.pool.event_seq):
                    got = [t.hash for t in cand.selected]
                    parent, attrs = cand.parent_hash, cand.attrs
                    break
        time.sleep(0.01)
    if got is None:
        return "producer never reached pool parity"
    clone = TransactionPool(node.pool.state_reader, config=node.pool.config)
    clone.base_fee = node.pool.base_fee
    clone.blob_base_fee = node.pool.blob_base_fee
    with node.pool._lock:
        ptxs = sorted(node.pool.by_hash.values(),
                      key=lambda p: p.submission_id)
    for p in ptxs:
        clone.add_transaction(p.tx, sender=p.sender)
    block, _fees = build_payload(node.tree, clone, parent, attrs)
    want = [t.hash for t in block.transactions]
    if got != want:
        return (f"candidate/serial inclusion set mismatch: candidate "
                f"{len(got)} txs, serial {len(want)} txs, first divergence "
                f"at rank {next((i for i, (a, b) in enumerate(zip(got, want)) if a != b), min(len(got), len(want)))}")
    return None


def run_txflow_mode() -> None:
    """RETH_TPU_BENCH_MODE=txflow: the production write path end-to-end —
    txpool firehose -> continuous block production (payload/producer.py)
    vs the same flood through the serial build-on-demand miner. At each
    offered load point an adversarial submission mix (nonce chains +
    duplicates + replacements + underpriced) floods the insertion batcher
    while the dev miner seals on an interval; the headline is the
    tx->inclusion p99 at the top rate with txs/block, shed counts, and the
    producer's incremental economy (fresh vs replayed ranks, hot-hit rate)
    in ``per_rate``. ACCEPTANCE CONTRACT: at every load point the hot
    candidate's inclusion set is verified bit-identical against one serial
    greedy build over a cloned pool BEFORE any number prints (divergence
    = rc 1). ``vs_baseline`` = serial-miner p99 / continuous p99 at the
    top rate. Hermetic (CPU dev node, numpy committer — never touches the
    tunnel). Env: RETH_TPU_BENCH_TXFLOW_RATES (default "1000,10000,50000"
    offered tx/s), RETH_TPU_BENCH_TXFLOW_WALLETS (default 10),
    RETH_TPU_BENCH_TXFLOW_TXS (chain length per wallet, default 6),
    RETH_TPU_BENCH_TXFLOW_INTERVAL (mining interval s, default 0.25)."""
    from reth_tpu.node import Node, NodeConfig
    from reth_tpu.pool.batcher import PoolOverloaded
    from reth_tpu.pool.pool import PoolError
    from reth_tpu.primitives.keccak import keccak256_batch_np
    from reth_tpu.primitives.types import Account
    from reth_tpu.testing import ChainBuilder, Wallet
    from reth_tpu.trie.committer import TrieCommitter

    rates = [int(r) for r in os.environ.get(
        "RETH_TPU_BENCH_TXFLOW_RATES", "1000,10000,50000").split(",") if r]
    n_wallets = int(os.environ.get("RETH_TPU_BENCH_TXFLOW_WALLETS", "10"))
    txs_per_wallet = int(os.environ.get("RETH_TPU_BENCH_TXFLOW_TXS", "6"))
    interval = float(os.environ.get("RETH_TPU_BENCH_TXFLOW_INTERVAL", "0.25"))
    _STATE["metric"] = "txflow_inclusion_p99_ms"
    _STATE["unit"] = "ms"
    _STATE["backend"] = "cpu"

    def make_node(continuous: bool):
        committer = TrieCommitter(hasher=keccak256_batch_np)
        committer.turbo_backend = "numpy"
        wallets = [Wallet(0xB100 + i) for i in range(n_wallets)]
        under_wallet = Wallet(0xBEEF)
        genesis = {w.address: Account(balance=10**21)
                   for w in wallets + [under_wallet]}
        builder = ChainBuilder(genesis, committer=committer)
        node = Node(NodeConfig(dev=True, genesis_header=builder.genesis,
                               genesis_alloc=builder.accounts_at_genesis,
                               continuous_build=continuous,
                               http_port=0, authrpc_port=0),
                    committer=committer)
        node.start_rpc()
        return node, wallets, under_wallet

    def run_point(continuous: bool, rate: int, seed: int) -> dict:
        rng = np.random.default_rng(seed)
        node, wallets, under_wallet = make_node(continuous)
        try:
            sched, _slots = _txflow_schedule(wallets, under_wallet,
                                             txs_per_wallet, rng, rate)
            sub_times: dict[bytes, tuple[float, bool]] = {}
            lats: list[float] = []
            counts = {"accepted": 0, "dup_rejected": 0,
                      "repl_rejected": 0, "sheds": 0}
            blocks = {"total": 0, "nonempty": 0, "mined": 0}
            mined_hashes: set[bytes] = set()
            pause = threading.Event()
            stop = threading.Event()
            miner_err: list = []

            def miner_loop():
                while not stop.is_set():
                    if stop.wait(interval):
                        return
                    if pause.is_set():
                        continue
                    try:
                        blk = node.miner.mine_block()
                    except Exception as e:  # noqa: BLE001 — surfaced below
                        miner_err.append(e)
                        return
                    now = time.monotonic()
                    blocks["total"] += 1
                    if blk.transactions:
                        blocks["nonempty"] += 1
                    for t in blk.transactions:
                        rec = sub_times.get(t.hash)
                        if rec is not None:
                            mined_hashes.add(t.hash)
                            blocks["mined"] += 1
                            if rec[1]:
                                lats.append(now - rec[0])

            mt = threading.Thread(target=miner_loop, daemon=True)
            mt.start()
            _STATE["phase"] = (f"txflow {rate}/s "
                               f"({'continuous' if continuous else 'serial'})"
                               f": flood")
            futs = []
            t0 = time.monotonic()
            for i, (kind, tx, track) in enumerate(sched):
                lag = t0 + i / rate - time.monotonic()
                if lag > 0:
                    time.sleep(lag)
                sub_times[tx.hash] = (time.monotonic(), track)
                futs.append((kind, tx, node.tx_batcher.submit(tx)))
            accepted: set[bytes] = set()
            for kind, tx, fut in futs:
                try:
                    fut.result(timeout=30)
                    counts["accepted"] += 1
                    accepted.add(tx.hash)
                except PoolOverloaded:
                    counts["sheds"] += 1
                except PoolError as e:
                    if kind == "dup":
                        counts["dup_rejected"] += 1
                    elif kind in ("repl", "repl_under"):
                        # "replacement underpriced", or "nonce too low"
                        # when the base won the race into a block first
                        counts["repl_rejected"] += 1
                    else:
                        raise RuntimeError(
                            f"txflow: unexpected rejection of a {kind} "
                            f"tx: {e}")
            # drain: every accepted slot mined, only the underpriced
            # straggler left pooled (it can never execute at this fee)
            _STATE["phase"] = (f"txflow {rate}/s: drain "
                               f"({'continuous' if continuous else 'serial'})")
            stragglers = sum(1 for k, t, _ in futs
                             if k == "under" and t.hash in accepted)
            deadline = time.time() + 90
            while time.time() < deadline and not miner_err:
                with node.pool._lock:
                    left = len(node.pool.by_hash)
                if left <= stragglers:
                    break
                time.sleep(0.02)
            else:
                if not miner_err:
                    raise RuntimeError(
                        f"txflow: pool never drained at {rate}/s "
                        f"({left} txs left, {stragglers} expected)")
            if miner_err:
                raise RuntimeError(f"txflow: miner failed: {miner_err[0]}")
            if continuous:
                # acceptance contract: pause mining, push one more
                # adversarial burst, and verify the refreshed candidate
                # bit-identical against a serial greedy build over a
                # cloned pool BEFORE this point's numbers count
                _STATE["phase"] = f"txflow {rate}/s: verify vs serial greedy"
                pause.set()
                burst, _ = _txflow_schedule(wallets, under_wallet,
                                            2, rng, rate + 1)
                bfuts = [(k, t, node.tx_batcher.submit(t))
                         for k, t, _tr in burst]
                for k, t, f in bfuts:
                    try:
                        f.result(timeout=30)
                        sub_times[t.hash] = (time.monotonic(), False)
                        accepted.add(t.hash)
                    except PoolError:
                        pass
                diag = _txflow_verify(node)
                if diag is not None:
                    _emit(0, 0, error=f"txflow at {rate}/s: {diag}",
                          exit_code=1)
                pause.clear()
                deadline = time.time() + 90
                while time.time() < deadline and not miner_err:
                    with node.pool._lock:
                        left = len(node.pool.by_hash)
                    if left <= stragglers + 1:  # + the burst's underpriced
                        break
                    time.sleep(0.02)
            stop.set()
            mt.join(timeout=10)
            if miner_err:
                raise RuntimeError(f"txflow: miner failed: {miner_err[0]}")
            if not lats:
                raise RuntimeError(f"txflow: no inclusion latencies at "
                                   f"{rate}/s")
            entry = {
                "p99_inclusion_ms": round(
                    float(np.percentile(lats, 99)) * 1e3, 2),
                "mean_inclusion_ms": round(
                    float(np.mean(lats)) * 1e3, 2),
                "txs_per_block": round(
                    blocks["mined"] / max(1, blocks["nonempty"]), 2),
                "blocks": blocks["total"],
                "nonempty_blocks": blocks["nonempty"],
                "mined": blocks["mined"],
                **counts,
                "batcher_sheds": node.tx_batcher.sheds,
            }
            if continuous and node.producer is not None:
                s = node.producer.snapshot()
                entry["producer"] = {
                    k: s[k] for k in ("refreshes", "full_rebuilds",
                                      "exec_ranks", "reexec_ranks",
                                      "invalidated", "hits", "misses",
                                      "sealed", "errors")}
                entry["miner_producer_seals"] = node.miner.producer_seals
                entry["miner_serial_builds"] = node.miner.serial_builds
            return entry
        finally:
            stop.set()
            node.stop()

    per_rate: dict[str, dict] = {}
    for rate in rates:
        entry = run_point(True, rate, seed=rate)
        entry["serial_miner"] = {
            k: v for k, v in run_point(False, rate, seed=rate).items()
            if k in ("p99_inclusion_ms", "mean_inclusion_ms",
                     "txs_per_block", "blocks", "mined")}
        per_rate[str(rate)] = entry
    top = per_rate[str(max(rates))]
    value = top["p99_inclusion_ms"]
    serial_p99 = top["serial_miner"]["p99_inclusion_ms"]
    _STATE["device_result"] = value
    _emit(value, round(serial_p99 / value, 3) if value else 0,
          per_rate=per_rate, rates=rates,
          txs_per_block=top["txs_per_block"],
          sheds=sum(per_rate[str(r)]["sheds"] for r in rates),
          wallets=n_wallets, chain_len=txs_per_wallet,
          mining_interval_s=interval,
          verified="candidate inclusion set bit-identical to a serial "
                   "greedy build over a cloned pool at every load point "
                   "before measuring",
          exit_code=0)


def _setup_compile_cache() -> None:
    """RETH_TPU_COMPILE_CACHE_DIR: validate (quarantining corruption) and
    enable the persistent XLA compilation cache, but ONLY after a
    subprocess probe proves this jax build can run with it — the cache has
    deadlocked the first jit over the axon tunnel before. The emitted
    ``compile_cache`` field splits cold (empty cache, compiles pay full
    wall) from warm (restart/rerun: compiles load from disk), so
    compile_wall_s is attributable."""
    cache_dir = os.environ.get("RETH_TPU_COMPILE_CACHE_DIR")
    if not cache_dir:
        return
    _STATE["phase"] = "compile-cache validation"
    try:
        from reth_tpu.ops.warmup import CompileCache

        cc = CompileCache(cache_dir)
        rep = cc.validate()
        state = "warm" if rep["entries"] else "cold"
        if cc.probe() and cc.enable():
            _STATE["compile_cache"] = {
                "dir": str(cc.dir), "state": state,
                "entries": rep["entries"],
                "quarantined": rep["quarantined"]}
            _STATE["_cache_obj"] = cc  # hands per-shape hit tracking to warm-up
        else:
            _STATE["compile_cache"] = {
                "dir": str(cc.dir), "state": "probe-failed-disabled"}
    except Exception as e:  # noqa: BLE001 — cache is never fatal to a bench
        _STATE["compile_cache"] = {"state": f"error: {e}"}


def _maybe_warmup() -> None:
    """RETH_TPU_WARMUP=background|block: run the real warm-up manager
    (ops/warmup.py) over the default shape menu before measuring, so the
    measured window is pure steady state and the line's ``warmup_state``
    carries the per-shape compile walls + cache hit/miss split."""
    mode = os.environ.get("RETH_TPU_WARMUP", "off")
    if mode == "off":
        return
    _STATE["phase"] = "managed warm-up (shape menu)"
    try:
        from reth_tpu.ops.warmup import WarmupManager

        # the cache (already validated + probe-enabled above) rides along
        # so per-shape cache hits/misses land in warmup_state
        mgr = WarmupManager(cache=_STATE.get("_cache_obj"),
                            verify_cache=False,
                            enable_cache=False)
        _STATE["warmup_mgr"] = mgr  # _emit snapshots it live
        if mode == "block":
            mgr.run()
        else:
            mgr.start()
            mgr.wait(timeout=_DEADLINE / 2)
    except Exception as e:  # noqa: BLE001 — warm-up is never fatal to a bench
        _STATE["warmup_state"] = {"state": f"error: {e}"}


def main():
    # record spans/events from the start: the flight-recorder excerpt in
    # any error line needs the trail (probe attempts, first compiles)
    from reth_tpu import tracing

    tracing.set_trace_enabled(True)
    inner = os.environ.get("RETH_TPU_BENCH_MESH_INNER")
    if inner:
        # mesh-mode subprocess: measure + verify, skip warm-up/cache setup
        # (the inner run attributes its own compile wall explicitly)
        _mesh_inner(int(inner))
        return
    inner = os.environ.get("RETH_TPU_BENCH_SUBTRIE_INNER")
    if inner:
        _subtrie_inner(int(inner))
        return
    _setup_compile_cache()
    _maybe_warmup()
    mode = os.environ.get("RETH_TPU_BENCH_MODE", "exec")
    if mode == "mesh":
        run_mesh_mode()
        return
    if mode == "subtrie":
        run_subtrie_mode()
        return
    if mode == "service":
        run_service_mode()
        return
    if mode == "sparse":
        run_sparse_mode()
        return
    if mode == "gateway":
        run_gateway_mode()
        return
    if mode == "fleet":
        run_fleet_mode()
        return
    if mode == "ha":
        run_ha_mode()
        return
    if mode == "txflow":
        run_txflow_mode()
        return
    if mode == "import":
        run_import_mode()
        return
    if mode == "hotstate":
        run_hotstate_mode()
        return
    if mode == "exec":
        # the DEFAULT: CPU-measurable optimistic parallel execution — the
        # perf trajectory records a real number with or without a device
        run_exec_mode()
        return
    n_accounts = int(os.environ.get("RETH_TPU_BENCH_ACCOUNTS", "150000"))
    n_slots = int(os.environ.get("RETH_TPU_BENCH_SLOTS", "60000"))
    tier = int(os.environ.get("RETH_TPU_BENCH_TIER", "16384"))

    t_start = time.time()
    diag = probe_tunnel()
    if diag is not None:
        # wedged/absent tunnel: the pipeline's CPU win must still be
        # CAPTURABLE — record the numpy-backend measurement and exit 0
        run_cpu_fallback(n_accounts, n_slots, diag)
        return
    # a late probe success means a recovering tunnel AND less watchdog
    # budget left — shrink the workload so the round still lands a number
    remaining = _DEADLINE - (time.time() - t_start)
    if (remaining < 600 and "RETH_TPU_BENCH_ACCOUNTS" not in os.environ
            and "RETH_TPU_BENCH_SLOTS" not in os.environ):
        n_accounts, n_slots = n_accounts // 3, n_slots // 3

    from reth_tpu.trie.turbo import TurboCommitter

    _STATE["backend"] = "device"
    _STATE["phase"] = "state build"
    storage_jobs, account_jobs = build_state(n_accounts, n_slots)

    # forced large min_tier => one or two batch tiers => <=~4 XLA programs
    dev_committer = TurboCommitter(backend="device", min_tier=tier)
    cpu_committer = TurboCommitter(backend="numpy")

    # warm-up = one full untimed run, so every program shape the measured
    # run dispatches is already compiled (XLA caches by shape in-process).
    # Its wall is reported as the compile side of the compile/steady split
    # (the per-shape detail rides in via the compile tracker).
    _STATE["phase"] = "device warm-up (compiles)"
    t_warm = time.time()
    run_rebuild(dev_committer, storage_jobs, account_jobs, pipelined=True)
    dt_warm = time.time() - t_warm
    if (_STATE.get("warmup_mgr") is None
            and _STATE.get("warmup_state", "off") == "off"):
        # no managed warm-up ran: the untimed full pass IS the warm-up —
        # still attributed, so this line can't masquerade as steady state
        _STATE["warmup_state"] = {"state": "bench-warm-pass",
                                  "wall_s": round(dt_warm, 3)}

    _STATE["phase"] = "device run"
    roots_dev, hashed_dev, dt_dev = run_rebuild(
        dev_committer, storage_jobs, account_jobs, pipelined=True)
    _STATE["device_result"] = round(hashed_dev / dt_dev, 1)
    _STATE["phase"] = "cpu baseline"
    roots_cpu, _hashed_cpu, dt_cpu = run_rebuild(
        cpu_committer, storage_jobs, account_jobs, pipelined=True)
    if roots_dev != roots_cpu:
        _emit(0, 0, error="device/cpu root mismatch", exit_code=1)

    _emit(round(hashed_dev / dt_dev, 1), round(dt_cpu / dt_dev, 3),
          device_wall_s=round(dt_dev, 3), baseline_wall_s=round(dt_cpu, 3),
          warmup_wall_s=round(dt_warm, 3),
          steady_hashes_per_sec=round(hashed_dev / dt_dev, 1))


if __name__ == "__main__":
    main()
