# Developer entry points (reference: Makefile + nextest in CI,
# .github/workflows/unit.yml).

# Parallel test run: xdist shards by FILE (port-isolated fixtures make
# files independent); JAX pinned to CPU so no shard can touch the axon
# tunnel. Override workers with TEST_WORKERS=n.
TEST_WORKERS ?= 6

.PHONY: test test-serial test-faults test-pipeline test-service test-sparse test-parallel test-gateway test-obs test-warmup test-health test-mesh test-subtrie test-chaos test-reorg test-fleet test-fleet-obs test-ha test-txflow test-import-pipeline test-hotstate native tsan-triebuild

test:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  python -m pytest tests -q -p no:cacheprovider \
	  -n $(TEST_WORKERS) --dist loadfile

test-serial:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  python -m pytest tests -q -p no:cacheprovider

# device-supervisor failover drill: probes, breaker, watchdog, mid-commit
# CPU failover + fault injection — CPU-only, no device required
test-faults:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  python -m pytest tests/test_supervisor.py -q -p no:cacheprovider

# shared hash service: continuous batching, priority lanes, backpressure,
# exclusive lease, and the RETH_TPU_FAULT_SERVICE_* overload/stall/failover
# drills — CPU-only, no device required
test-service:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  python -m pytest tests/test_hash_service.py -q -p no:cacheprovider

# parallel sparse commit: randomized packed-vs-serial differential parity
# (bit-identical roots across updates/deletes/wipes, blinded + preserved
# edges), encode/proof pool-size sweeps, a threaded stress drill over a
# shared committer, and the RETH_TPU_FAULT_SPARSE_* abort/wedge fault
# drills (fallback to the incremental committer) — CPU-only
test-sparse:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  python -m pytest tests/test_sparse_parallel.py tests/test_sparse.py \
	  tests/test_sparse_root_engine.py tests/test_hotstate.py \
	  -q -p no:cacheprovider

# hot-state plane (ISSUE 19): cross-block trie-node cache
# (trie/hot_cache.py) + device-resident digest arena (DigestArena in
# ops/fused_commit.py). Hash-keyed cache versioning, keccak validation
# (RETH_TPU_FAULT_HOTSTATE_POISON must be CAUGHT), the 10-seed
# cached-vs-uncached randomized differential (roots bit-identical over
# interleaved update/delete/wipe streams + fork switches), arena epoch
# eviction / fault-fallback / EVICT_STORM drills, sibling-fork engine
# integration, and the hotstate_* metrics + degrade-only SLO rule —
# CPU-only
test-hotstate:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  python -m pytest tests/test_hotstate.py -q -p no:cacheprovider

# optimistic parallel execution (part of the default `make test` sweep):
# randomized differential parity vs the serial executor across conflict
# rates / worker counts / coinbase-sensitive ranks / mid-block reverts,
# the BAL + native-core equivalence suites it builds on, the
# RETH_TPU_FAULT_EXEC_* conflict-storm and rank-wedge drills (serial
# fallback ladder), and a threaded stress run over the shared native
# core — CPU-only
test-parallel:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  python -m pytest tests/test_parallel_exec.py tests/test_bal.py \
	  tests/test_native_exec.py -q -p no:cacheprovider

# RPC serving gateway: threaded coalescing stress (bit-identical to the
# ungated path), priority/shed behavior under full queues, head-change
# cache invalidation, RETH_TPU_FAULT_GATEWAY_* drills, and HTTP/WS/IPC
# one-gateway transport parity — CPU-only
test-gateway:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  python -m pytest tests/test_gateway.py -q -p no:cacheprovider

# block-lifecycle observability (part of the default `make test` flow —
# tests/ is swept wholesale): trace-context propagation + per-block
# timelines, flight-recorder dumps on RETH_TPU_FAULT_* drills, Chrome /
# OTLP span-file validation, /metrics exposition-format checks, the
# metrics thread-safety hammer, and the tracing-disabled overhead guard
# (span cost < 1% of the sparse-commit wall) — CPU-only
test-obs:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  python -m pytest tests/test_observability.py tests/test_fleet_obs.py \
	  -q -p no:cacheprovider -m 'not slow'

# fleet observability plane: trace wire-form encode/decode + adoption
# (feed frames, routed-RPC traceparent), Chrome-trace stitching across
# >=3 pids, metrics-federation delta protocol + bucket-exact histogram
# merge (randomized property test) + stale degradation, correlated
# flight dumps fanned over the feed under RETH_TPU_FAULT_REPLICA_WEDGE,
# the fleet SLO rules, and the federation/wire-form overhead guards;
# the @slow half runs the chaos --domain fleet wedge drill end-to-end
# (3 processes, stitched trace + bucket-exact scope=fleet + one
# correlation id across all three dumps) — CPU-only
test-fleet-obs:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  python -m pytest tests/test_fleet_obs.py -q -p no:cacheprovider

# node health & SLO engine (part of the default `make test` flow —
# tests/ is swept wholesale): metric ring-buffer retention + windowed
# quantiles, the burn-rate evaluator (degraded within one window,
# failing on sustained burn, hysteretic recovery), breach flight dumps +
# the RETH_TPU_FAULT_SLO_BREACH drill, /health + debug_healthCheck /
# debug_sloStatus / debug_metricsHistory end-to-end on a dev node with
# a hash-service stall, the bench perf-regression sentinel (wedged
# tunnel simulated -> rc=0 with a real CPU number + vs_prev), and the
# sampler/evaluator overhead guard (<1% of the sparse-commit wall) —
# CPU-only, no device required
test-health:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  python -m pytest tests/test_health.py -q -p no:cacheprovider

# mesh-sharded hash service: partition-rule routed sharded dispatch,
# randomized mesh-vs-single-device differential parity (incl. non-pow2
# meshes / uneven tiers), sub-mesh rebuild leases with live traffic
# continuing, the per-device breaker shrink+replay ladder under
# RETH_TPU_FAULT_DEVICE_WEDGE, mesh warm-up menu variants, and the
# RETH_TPU_BENCH_MODE=mesh end-to-end drill — CPU-only (8 virtual
# host devices via conftest)
test-mesh:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  python -m pytest tests/test_mesh_service.py tests/test_parallel.py \
	  -q -p no:cacheprovider
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  python -m pytest tests/test_fused_commit.py tests/test_turbo_commit.py \
	  -q -p no:cacheprovider -m 'not slow'

# device warm-up manager: shape-menu AOT compile lifecycle (watchdog +
# backoff retry under the RETH_TPU_FAULT_COMPILE_WEDGE drill, degraded
# CPU serving, promotion after recovery), persistent-cache validation /
# corruption quarantine / subprocess cache probes, and the keccak/fused
# tier clamps — CPU-only, no device required
test-warmup:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  python -m pytest tests/test_warmup.py -q -p no:cacheprovider

# consensus robustness: orphan BlockBuffer bound/TTL + buffered-child
# replay, invalid-cache LRU bound (incl. the @slow 10k-payload flood
# acceptance drill), fcU cancellation of in-flight inserts with a
# wedged proof worker held across the fcU, reorg-storm detection +
# speculation backoff, deep-reorg depth accounting, and the
# ForkBuilder/tamper machinery the chaos consensus domain drives —
# CPU-only (tier-1 runs the same files minus the @slow flood)
test-reorg:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  python -m pytest tests/test_consensus_robustness.py \
	  tests/test_engine_tree.py tests/test_sparse_root_engine.py \
	  -q -p no:cacheprovider

# crash-safe persistence + chaos drills: WAL format/replay/checkpoint
# units, corrupt-image quarantine, reorg-across-restart, and the @slow
# subprocess matrix — kill -9 at EVERY declared crash point
# (RETH_TPU_FAULT_CRASH_AT), raw SIGKILL mid-mining, the 10-seed
# composed-injector storage campaign AND the 10-seed Engine-API
# consensus campaign (seeded reorg storms vs a fault-free twin; seeds
# printed on failure for exact replay via `python -m reth_tpu.chaos
# scenario --domain storage|consensus --seed N`), the deep-reorg-
# across-threshold SIGKILL drill, and the deliberately-broken
# torn-record-accepted drill proving the invariant suite can fail.
# Kill drills are `-m slow` so tier-1 keeps its budget; this target
# runs everything — including the fleet domain's replica-kill-mid-load
# drills (tests/test_fleet.py) and the hot-state cache dimension
# (half the consensus seeds storm a --hot-state node against an
# uncached twin; POISON/EVICT_STORM injectors; zero leaked arena
# rows post-storm) — CPU-only, no device required
test-chaos:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  python -m pytest tests/test_wal_recovery.py tests/test_chaos.py \
	  tests/test_fleet.py tests/test_fleet_obs.py tests/test_ha.py \
	  tests/test_block_pipeline.py tests/test_txflow.py \
	  tests/test_hotstate.py -q -p no:cacheprovider

# production write path: txpool firehose -> continuous block production.
# Randomized differential producer-vs-serial-greedy parity (clone-pool
# bit-identity at pool-sequence parity), nonce-gap promotion mid-build,
# blob-tx fee gating, replacement-racing-inclusion slot accounting,
# TxBatcher backpressure (-32005 + retry_after + shed metrics), pt_*
# feed framing + replica pending-view reads, classify() pinning for
# producer_/txpool_, scenario determinism, plus the @slow multi-process
# drills: the SIGKILL-mid-build pool chaos domain (10 seeds, `python -m
# reth_tpu.chaos campaign --domain pool`) and the
# RETH_TPU_BENCH_MODE=txflow end-to-end capture — CPU-only
test-txflow:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  python -m pytest tests/test_txflow.py -q -p no:cacheprovider

# cross-block import pipeline (engine/block_pipeline.py): randomized
# serial-vs-pipelined differential imports (roots/receipts/senders
# bit-identical), deterministic mid-commit speculation via a gated
# commit leg, the abort ladder (tampered-root parent, fcU reorg
# mid-speculation), lease hygiene, and depth plumbing — CPU-only.
# The consensus chaos domain storms depth-2 trees on half its seeds
# (see test-chaos / `python -m reth_tpu.chaos campaign --domain
# consensus`); RETH_TPU_BENCH_MODE=import is the perf capture.
test-import-pipeline:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  python -m pytest tests/test_block_pipeline.py -q -p no:cacheprovider

# leader/standby high availability: promotion state machine + heartbeat
# monitor units, wire-framing corruption vetting (torn/CRC/stale-epoch/
# out-of-order-generation rejected exactly like on-disk replay),
# flapping-feed client backoff + resubscribe-from-last-seen-head, the
# fleet_promote/fleet_standbyStatus ENGINE admission pinning, live
# leader->standby WAL shipping + in-process promotion, plus the @slow
# multi-process drills: the SIGKILL-the-leader chaos domain (10 seeds,
# `python -m reth_tpu.chaos campaign --domain ha`), the no-fence
# negative drill proving the suite can fail, and the
# RETH_TPU_BENCH_MODE=ha end-to-end capture — CPU-only
test-ha:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  python -m pytest tests/test_ha.py -q -p no:cacheprovider

# stateless read-replica fleet: consistent-hash ring units (stability,
# failover order), witness-feed CRC framing, router draining ladder
# (lag/wedge/transport-dead -> shed -> hysteretic heal) over fake
# replicas, a live fleet-mode dev node with a witness-fed replica
# serving eth_call/eth_estimateGas/eth_getProof/eth_getLogs/
# eth_getBlockBy* bit-identical to the full node (late-joiner blinded
# reads -> -32001 -> gateway failover), plus the @slow multi-process
# drills: SIGKILL-a-replica-mid-load, the 10-seed fleet chaos campaign,
# and the RETH_TPU_BENCH_MODE=fleet end-to-end capture — CPU-only
test-fleet:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  python -m pytest tests/test_fleet.py -q -p no:cacheprovider

# whole-subtrie fused tree-hash kernels: k-level engine parity vs the
# per-level engines and the numpy twin (k x depth x mesh grid incl.
# non-pow2 6/3-device meshes), the RETH_TPU_FAULT_SUBTRIE_{WEDGE,ABORT}
# fused->per-level->CPU fault ladder, the hoisted ladder-cap regression
# (64-level branch-heavy window stays on-menu), warm-up k-shape routing,
# and hash-service multi-level window requests. The compile-heavy
# k-sweeps are `-m slow` so tier-1 keeps its budget; this target runs
# everything — CPU-only (8 virtual host devices via conftest)
test-subtrie:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  python -m pytest tests/test_subtrie_fused.py -q -p no:cacheprovider

# overlapped rebuild pipeline: parity vs the serial committer, packing,
# arena residency, abort/failover drills, chunked-resume — fast, CPU-only
# (the sanitizer stress build is `-m slow`; run it via tsan-triebuild);
# the whole-subtrie k-level backend rides along (it is a pipeline
# backend: flush_window per packed window)
test-pipeline: test-subtrie
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  python -m pytest tests/test_turbo_pipeline.py tests/test_merkle_resume.py \
	  -q -p no:cacheprovider -m 'not slow'

native:
	mkdir -p native/build
	g++ -O2 -std=c++17 -shared -fPIC native/triebuild.cpp -o native/build/libtriebuild.so
	g++ -O2 -std=c++17 -shared -fPIC native/secp256k1.cpp -o native/build/libsecp.so
	g++ -O2 -std=c++17 -shared -fPIC native/kvstore.cpp -o native/build/libkvstore.so
	g++ -O2 -std=c++17 -shared -fPIC native/pagedkv.cpp -o native/build/libpagedkv.so
	g++ -O2 -std=c++17 -shared -fPIC -pthread native/evmexec.cpp -o native/build/libevmexec.so

# threaded stress of the native structure sweep under TSAN (the rebuild
# pipeline calls rtb_build from a thread pool); mirrors kvstore_tsan.cpp.
# Where gcc's libtsan breaks on the running kernel, build with
# -fsanitize=address,undefined instead (tests/test_turbo_pipeline.py
# probes and picks automatically).
tsan-triebuild:
	mkdir -p native/build
	g++ -std=c++17 -O1 -g -fsanitize=thread \
	  native/triebuild.cpp native/triebuild_tsan.cpp -o native/build/triebuild_stress
	./native/build/triebuild_stress
