# Developer entry points (reference: Makefile + nextest in CI,
# .github/workflows/unit.yml).

# Parallel test run: xdist shards by FILE (port-isolated fixtures make
# files independent); JAX pinned to CPU so no shard can touch the axon
# tunnel. Override workers with TEST_WORKERS=n.
TEST_WORKERS ?= 6

.PHONY: test test-serial test-faults native

test:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  python -m pytest tests -q -p no:cacheprovider \
	  -n $(TEST_WORKERS) --dist loadfile

test-serial:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  python -m pytest tests -q -p no:cacheprovider

# device-supervisor failover drill: probes, breaker, watchdog, mid-commit
# CPU failover + fault injection — CPU-only, no device required
test-faults:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  python -m pytest tests/test_supervisor.py -q -p no:cacheprovider

native:
	mkdir -p native/build
	g++ -O2 -std=c++17 -shared -fPIC native/triebuild.cpp -o native/build/libtriebuild.so
	g++ -O2 -std=c++17 -shared -fPIC native/secp256k1.cpp -o native/build/libsecp.so
	g++ -O2 -std=c++17 -shared -fPIC native/kvstore.cpp -o native/build/libkvstore.so
	g++ -O2 -std=c++17 -shared -fPIC native/pagedkv.cpp -o native/build/libpagedkv.so
	g++ -O2 -std=c++17 -shared -fPIC -pthread native/evmexec.cpp -o native/build/libevmexec.so
