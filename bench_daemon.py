"""In-session opportunistic TPU bench capture daemon.

Round-5 answer to four consecutive rounds of BENCH = 0: instead of betting
the headline number on the driver's single end-of-round window (which has
hit a wedged axon tunnel every round), this daemon runs for the WHOLE
session and grabs the number at the first healthy window.

Strategy (VERDICT.md round 4, "Next round" #1):
- every ~10 min, probe the tunnel in a subprocess: ONE tiny pre-compiled
  program, hard 75 s budget (memory: giant compiles wedge the tunnel for
  hours; a probe timeout means wedged, not transient),
- on the first healthy probe, run a *micro* bench (40k accounts, one
  forced fused tier => <=~4 small XLA programs, ~2 min device time) via
  bench.py in a subprocess, write ``BENCH_SELF_r05.json`` and git-commit
  it immediately,
- escalate to the bigger sizes (150k, then 400k accounts) only while the
  tunnel stays healthy, updating the artifact with the full size curve,
- append every probe/bench event to ``BENCH_PROBELOG_r05.jsonl`` and
  commit the log hourly even when every probe fails, so the round records
  the capture attempts either way.

Reference analogue: the number being captured matches the reference's
MerkleStage rebuild hot path (crates/stages/stages/src/stages/
hashing_account.rs:29-32, crates/trie/sparse/src/arena/mod.rs:2500-2548).

Run detached from the top of the session:
    python bench_daemon.py >/tmp/bench_daemon.out 2>&1 &
"""

from __future__ import annotations

import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
LOG = os.path.join(REPO, "BENCH_PROBELOG_r05.jsonl")
ARTIFACT = os.path.join(REPO, "BENCH_SELF_r05.json")

PROBE_BUDGET_S = int(os.environ.get("RETH_TPU_DAEMON_PROBE_BUDGET", "75"))
PROBE_GAP_S = int(os.environ.get("RETH_TPU_DAEMON_PROBE_GAP", "600"))
HEALTHY_GAP_S = 60  # between escalation stages while the tunnel is up
LOG_COMMIT_EVERY = 6  # probes (~hourly at the default gap)

# (accounts, slots, fused tier, bench watchdog seconds) — smallest first so
# the first healthy window lands SOME number before anything ambitious.
SIZES = [
    (40_000, 16_000, 16_384, 420),
    (150_000, 60_000, 16_384, 900),
    (400_000, 160_000, 32_768, 1500),
]

# Deliberately duplicates bench.py's probe snippet: importing bench.py would
# start its module-level watchdog thread, which os._exit()s the process after
# RETH_TPU_BENCH_TIMEOUT — fatal for a daemon meant to live all session.
_PROBE_CODE = (
    "import jax, jax.numpy as jnp\n"
    "d = jax.devices()\n"
    "y = jax.jit(lambda a: a ^ (a << 1))(jnp.arange(256, dtype=jnp.uint32))\n"
    "y.block_until_ready()\n"
    "print('PROBE_OK', d[0].platform, flush=True)\n"
)


def _now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).isoformat(timespec="seconds")


def log_event(rec: dict) -> None:
    rec = {"ts": _now(), **rec}
    with open(LOG, "a") as f:
        f.write(json.dumps(rec) + "\n")


def git_commit(paths: list[str], msg: str) -> bool:
    """Commit ONLY the named paths (pathspec commit — ignores whatever the
    interactive session has staged), retrying briefly on index-lock races.
    The add is required first: a pathspec commit can't see untracked files."""
    for attempt in range(5):
        subprocess.run(["git", "-C", REPO, "add", "--"] + paths,
                       capture_output=True, text=True)
        r = subprocess.run(
            ["git", "-C", REPO, "commit", "-m", msg, "--"] + paths,
            capture_output=True, text=True,
        )
        if r.returncode == 0:
            return True
        out = (r.stdout + r.stderr).lower()
        if "nothing to commit" in out or "no changes added" in out:
            return False
        time.sleep(3 + attempt * 3)
    # don't leave our paths staged for the interactive session's next
    # unrelated commit to sweep in
    subprocess.run(["git", "-C", REPO, "restore", "--staged", "--"] + paths,
                   capture_output=True, text=True)
    log_event({"event": "git_commit_failed", "msg": msg, "stderr": r.stderr[-400:]})
    return False


def probe() -> tuple[bool, str]:
    try:
        r = subprocess.run(
            [sys.executable, "-u", "-c", _PROBE_CODE],
            capture_output=True, text=True, timeout=PROBE_BUDGET_S,
        )
    except subprocess.TimeoutExpired:
        return False, f"probe exceeded {PROBE_BUDGET_S}s (wedged tunnel)"
    if r.returncode == 0 and "PROBE_OK" in r.stdout:
        return True, r.stdout.strip().splitlines()[-1]
    tail = (r.stderr or r.stdout).strip().splitlines()[-1:] or ["no output"]
    return False, f"rc={r.returncode}: {tail[0][:300]}"


def run_bench(accounts: int, slots: int, tier: int, watchdog: int) -> dict | None:
    env = dict(
        os.environ,
        RETH_TPU_BENCH_ACCOUNTS=str(accounts),
        RETH_TPU_BENCH_SLOTS=str(slots),
        RETH_TPU_BENCH_TIER=str(tier),
        RETH_TPU_BENCH_TIMEOUT=str(watchdog),
        # the daemon just probed healthy — skip bench.py's long retry ladder
        RETH_TPU_PROBE_TIMEOUT="90",
        RETH_TPU_PROBE_ATTEMPTS="1",
    )
    # persistent compile cache shared across capture attempts (and sessions):
    # the first healthy window pays the compiles, every retry/escalation
    # after it loads from disk — so compile wall attributes to one run
    # instead of silently taxing each, and warmup_state records which
    env.setdefault("RETH_TPU_COMPILE_CACHE_DIR",
                   os.path.join(REPO, ".compile-cache"))
    env.setdefault("RETH_TPU_WARMUP", "block")
    # trailing-baseline store shared across captures/sessions: every
    # bench line carries vs_prev/regression vs the last-N good runs of
    # the same metric+mode+backend+warmup key (perf-regression sentinel)
    env.setdefault("RETH_TPU_BENCH_BASELINE_STORE",
                   os.path.join(REPO, ".bench_baselines.json"))
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, timeout=watchdog + 90, env=env, cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        return {"value": 0, "warmup_state": "unknown",
                "dispatches_per_block": 0, "pipeline_depth": 1, "overlap_fraction": 0,
                "error": f"bench subprocess exceeded {watchdog + 90}s"}
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
        except (json.JSONDecodeError, ValueError):
            continue
        if isinstance(parsed, dict):
            # a zero must never land in the log without its warm-up
            # attribution (five rounds of bare wedged-tunnel zeros);
            # every line also carries its mesh topology — single-device
            # captures are honestly n_devices=1, mesh captures report
            # their size + how many devices were shed by breakers
            parsed.setdefault("warmup_state", "unknown")
            parsed.setdefault("n_devices", 1)
            parsed.setdefault("mesh_degraded", 0)
            parsed.setdefault("dispatches_per_block", 0)
            parsed.setdefault("pipeline_depth", 1)
            parsed.setdefault("overlap_fraction", 0)
            return parsed
    return {"value": 0, "warmup_state": "unknown", "n_devices": 1,
            "mesh_degraded": 0, "dispatches_per_block": 0, "pipeline_depth": 1, "overlap_fraction": 0,
            "error": f"no JSON line, rc={r.returncode}: "
                     f"{(r.stderr or '')[-300:]}"}


def run_mesh_bench(watchdog: int = 900) -> dict | None:
    """RETH_TPU_BENCH_MODE=mesh capture: the production rebuild loop over
    1/2/4/8 SIMULATED host devices. Hermetic — the mode forces
    JAX_PLATFORMS=cpu in its per-size subprocesses and never touches the
    tunnel — so it runs once at daemon start regardless of probe health
    and every session records the sharded data plane's scaling curve."""
    env = dict(os.environ,
               RETH_TPU_BENCH_MODE="mesh",
               RETH_TPU_BENCH_TIMEOUT=str(watchdog))
    env.setdefault("RETH_TPU_BENCH_BASELINE_STORE",
                   os.path.join(REPO, ".bench_baselines.json"))
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, timeout=watchdog + 120,
            env=env, cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        return {"value": 0, "n_devices": 0, "mesh_degraded": 0,
                "error": f"mesh bench exceeded {watchdog + 120}s"}
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
        except (json.JSONDecodeError, ValueError):
            continue
        if isinstance(parsed, dict):
            parsed.setdefault("n_devices", 0)
            parsed.setdefault("mesh_degraded", 0)
            parsed.setdefault("dispatches_per_block", 0)
            parsed.setdefault("pipeline_depth", 1)
            parsed.setdefault("overlap_fraction", 0)
            return parsed
    return {"value": 0, "n_devices": 0, "mesh_degraded": 0,
            "dispatches_per_block": 0, "pipeline_depth": 1, "overlap_fraction": 0,
            "error": f"mesh bench: no JSON line, rc={r.returncode}: "
                     f"{(r.stderr or '')[-300:]}"}


def run_fleet_bench(watchdog: int = 900) -> dict | None:
    """RETH_TPU_BENCH_MODE=fleet capture: sustained RPC throughput +
    p99 through the fleet gateway at 1/2/4/8 witness-fed replica
    subprocesses vs the single-node gateway. Hermetic (CPU dev node +
    local subprocesses, never touches the tunnel), so it runs at daemon
    start and every session records the serving fleet's scaling curve
    (``per_fleet``/``single_node``/``fleet_scaling``)."""
    env = dict(os.environ,
               RETH_TPU_BENCH_MODE="fleet",
               JAX_PLATFORMS="cpu",
               RETH_TPU_BENCH_TIMEOUT=str(watchdog))
    env.setdefault("RETH_TPU_BENCH_BASELINE_STORE",
                   os.path.join(REPO, ".bench_baselines.json"))
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, timeout=watchdog + 120,
            env=env, cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        return {"value": 0, "per_fleet": {}, "fleet_scaling": 0,
                "error": f"fleet bench exceeded {watchdog + 120}s"}
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
        except (json.JSONDecodeError, ValueError):
            continue
        if isinstance(parsed, dict):
            parsed.setdefault("per_fleet", {})
            parsed.setdefault("single_node", {})
            parsed.setdefault("fleet_scaling", 0)
            parsed.setdefault("dispatches_per_block", 0)
            parsed.setdefault("pipeline_depth", 1)
            parsed.setdefault("overlap_fraction", 0)
            return parsed
    return {"value": 0, "per_fleet": {}, "fleet_scaling": 0,
            "dispatches_per_block": 0, "pipeline_depth": 1, "overlap_fraction": 0,
            "error": f"fleet bench: no JSON line, rc={r.returncode}: "
                     f"{(r.stderr or '')[-300:]}"}


def run_txflow_bench(watchdog: int = 900) -> dict | None:
    """RETH_TPU_BENCH_MODE=txflow capture: the production write path —
    adversarial submission floods through the insertion batcher into the
    continuous block producer vs the serial build-on-demand miner, with
    tx->inclusion p99 + txs/block per offered load point and the
    candidate inclusion set verified bit-identical against a serial
    greedy build before any number prints. Hermetic (CPU dev node, numpy
    committer, never touches the tunnel), so it runs at daemon start and
    every session records the write path's latency curve (``per_rate``/
    ``txs_per_block``/``sheds``)."""
    env = dict(os.environ,
               RETH_TPU_BENCH_MODE="txflow",
               JAX_PLATFORMS="cpu",
               RETH_TPU_BENCH_TIMEOUT=str(watchdog))
    env.setdefault("RETH_TPU_BENCH_BASELINE_STORE",
                   os.path.join(REPO, ".bench_baselines.json"))
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, timeout=watchdog + 120,
            env=env, cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        return {"value": 0, "per_rate": {}, "txs_per_block": 0, "sheds": 0,
                "error": f"txflow bench exceeded {watchdog + 120}s"}
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
        except (json.JSONDecodeError, ValueError):
            continue
        if isinstance(parsed, dict):
            parsed.setdefault("per_rate", {})
            parsed.setdefault("txs_per_block", 0)
            parsed.setdefault("sheds", 0)
            parsed.setdefault("dispatches_per_block", 0)
            parsed.setdefault("pipeline_depth", 1)
            parsed.setdefault("overlap_fraction", 0)
            return parsed
    return {"value": 0, "per_rate": {}, "txs_per_block": 0, "sheds": 0,
            "dispatches_per_block": 0, "pipeline_depth": 1,
            "overlap_fraction": 0,
            "error": f"txflow bench: no JSON line, rc={r.returncode}: "
                     f"{(r.stderr or '')[-300:]}"}


def run_hotstate_bench(watchdog: int = 900) -> dict | None:
    """RETH_TPU_BENCH_MODE=hotstate capture: sustained sibling-fork
    import with the hot-state plane (cross-block trie-node cache +
    device digest arena) on vs off — proof-target reduction factor as
    the headline, cache hit rate, proof walls, per-block H2D bytes and
    the delta-upload fraction on the line, every payload VALID
    (root-checked) in both runs before any number prints. Hermetic (CPU
    jax backend, in-memory trees), so every session records the cache's
    effect on the steady-import read wall."""
    env = dict(os.environ,
               RETH_TPU_BENCH_MODE="hotstate",
               JAX_PLATFORMS="cpu",
               RETH_TPU_BENCH_TIMEOUT=str(watchdog))
    env.setdefault("RETH_TPU_BENCH_BASELINE_STORE",
                   os.path.join(REPO, ".bench_baselines.json"))
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, timeout=watchdog + 120,
            env=env, cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        return {"value": 0, "cache_hit_rate": 0,
                "delta_upload_fraction": None,
                "error": f"hotstate bench exceeded {watchdog + 120}s"}
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
        except (json.JSONDecodeError, ValueError):
            continue
        if isinstance(parsed, dict):
            parsed.setdefault("cache_hit_rate", 0)
            parsed.setdefault("cache_unblinds", 0)
            parsed.setdefault("delta_upload_fraction", None)
            parsed.setdefault("uncached_proof_targets_per_block", 0)
            parsed.setdefault("cached_proof_targets_per_block", 0)
            parsed.setdefault("uncached_h2d_bytes_per_block", 0)
            parsed.setdefault("cached_h2d_bytes_per_block", 0)
            parsed.setdefault("arena_delta_epochs", 0)
            parsed.setdefault("arena_faults", 0)
            return parsed
    return {"value": 0, "cache_hit_rate": 0, "delta_upload_fraction": None,
            "uncached_proof_targets_per_block": 0,
            "cached_proof_targets_per_block": 0,
            "error": f"hotstate bench: no JSON line, rc={r.returncode}: "
                     f"{(r.stderr or '')[-300:]}"}


def update_artifact(captures: list[dict]) -> None:
    best = max((c for c in captures if c["result"].get("value", 0) > 0),
               key=lambda c: c["accounts"], default=None)
    art = {
        "metric": "merkle_rebuild_keccak_per_sec",
        "value": best["result"]["value"] if best else 0,
        "unit": "hashes/s",
        "vs_baseline": best["result"].get("vs_baseline", 0) if best else 0,
        # perf-regression sentinel fields: how this capture compares to
        # the trailing last-N good runs of the same bench key
        "vs_prev": best["result"].get("vs_prev") if best else None,
        "regression": (best["result"].get("regression", False)
                       if best else False),
        "accounts": best["accounts"] if best else 0,
        "warmup_state": (best["result"].get("warmup_state", "unknown")
                         if best else "unknown"),
        "compile_cache": (best["result"].get("compile_cache", "off")
                          if best else "off"),
        "captured_at": _now(),
        "captures": captures,
        "note": "self-captured in-session by bench_daemon.py at the first "
                "healthy tunnel window (round-5 directive #1)",
    }
    with open(ARTIFACT, "w") as f:
        json.dump(art, f, indent=1)
        f.write("\n")


def main() -> None:
    log_event({"event": "daemon_start", "pid": os.getpid(),
               "probe_gap_s": PROBE_GAP_S, "sizes": SIZES})
    # mesh scaling curve first: hermetic (simulated host devices), so it
    # lands a number whether or not the tunnel ever probes healthy
    log_event({"event": "mesh_bench_start"})
    mesh_result = run_mesh_bench()
    log_event({"event": "mesh_bench_done", "result": mesh_result})
    git_commit([LOG], "bench: mesh-mode scaling capture "
                      f"({mesh_result.get('n_devices', 0)} devices, "
                      f"{mesh_result.get('value', 0)} hashes/s)")
    # replica-fleet serving curve: also hermetic (CPU dev node + local
    # replica subprocesses), so every session records it too
    log_event({"event": "fleet_bench_start"})
    fleet_result = run_fleet_bench()
    log_event({"event": "fleet_bench_done", "result": fleet_result})
    git_commit([LOG], "bench: fleet-mode serving capture "
                      f"({fleet_result.get('fleet_scaling', 0)}x scaling, "
                      f"{fleet_result.get('value', 0)} requests/s)")
    # write-path latency curve: hermetic too (CPU dev node + the
    # continuous producer), so every session records tx->inclusion p99
    log_event({"event": "txflow_bench_start"})
    txflow_result = run_txflow_bench()
    log_event({"event": "txflow_bench_done", "result": txflow_result})
    git_commit([LOG], "bench: txflow-mode write-path capture "
                      f"({txflow_result.get('value', 0)} ms inclusion p99, "
                      f"{txflow_result.get('txs_per_block', 0)} txs/block)")
    # hot-state plane curve: hermetic as well (CPU jax backend,
    # in-memory trees), so every session records the cross-block
    # cache's proof-target reduction + delta-upload fraction
    log_event({"event": "hotstate_bench_start"})
    hotstate_result = run_hotstate_bench()
    log_event({"event": "hotstate_bench_done", "result": hotstate_result})
    git_commit([LOG], "bench: hotstate-mode cache capture "
                      f"({hotstate_result.get('value', 0)}x fewer proof "
                      "targets, hit rate "
                      f"{hotstate_result.get('cache_hit_rate', 0)})")
    captures: list[dict] = []
    stage = 0
    probes = 0
    while True:
        probes += 1
        ok, diag = probe()
        log_event({"event": "probe", "n": probes, "ok": ok, "diag": diag})
        if ok and stage < len(SIZES):
            accounts, slots, tier, watchdog = SIZES[stage]
            log_event({"event": "bench_start", "accounts": accounts,
                       "slots": slots, "tier": tier})
            result = run_bench(accounts, slots, tier, watchdog)
            log_event({"event": "bench_done", "accounts": accounts,
                       "result": result})
            if result and result.get("regression"):
                # a regressed capture is still a capture, but the log
                # must say so LOUDLY — the sentinel exists because five
                # rounds of silent zeros erased the trajectory
                log_event({"event": "bench_regression",
                           "accounts": accounts,
                           "value": result.get("value"),
                           "vs_prev": result.get("vs_prev")})
            # a watchdog-truncated run (value>0 but "error" set, baseline
            # unmeasured) is not a clean capture — retry, don't escalate
            if result and result.get("value", 0) > 0 and "error" not in result:
                captures.append({"accounts": accounts, "slots": slots,
                                 "tier": tier, "ts": _now(), "result": result})
                update_artifact(captures)
                git_commit(
                    [ARTIFACT, LOG],
                    f"bench: self-captured TPU number at {accounts} accounts "
                    f"({result['value']} hashes/s, {result.get('vs_baseline')}x "
                    f"vs numpy baseline)",
                )
                stage += 1
                if stage == len(SIZES):
                    log_event({"event": "daemon_done",
                               "captures": len(captures)})
                    git_commit([LOG], "bench: capture-daemon finished — "
                                      "full size curve captured")
                    return
                time.sleep(HEALTHY_GAP_S)
                continue
            # bench failed despite a healthy probe — log and retry the same
            # stage on the next cycle rather than burning the window further
        if probes % LOG_COMMIT_EVERY == 0:
            git_commit([LOG], f"bench: capture-daemon probe log "
                              f"({probes} probes, {len(captures)} captures)")
        time.sleep(PROBE_GAP_S)


if __name__ == "__main__":
    main()
