// Native EVM wave executor: the nogil execution core behind BAL parallel
// block execution (reth_tpu/engine/bal.py).
//
// Reference analogue: revm v41 is the reference's native interpreter
// (reth Cargo.toml:430); this is the TPU-build equivalent for the flat
// transaction shapes that dominate blocks (value transfers and
// storage/compute contract calls without sub-calls). A WAVE of
// conflict-free transactions executes on real OS threads against an
// immutable snapshot table (accounts/slots/codes the Python side
// preloads from the BAL access hint); each thread keeps private write
// sets. Anything outside the snapshot or the supported opcode subset
// aborts that transaction with MISS and Python re-runs it through the
// full interpreter — the native path is an accelerator, never a
// semantics fork. Gas accounting mirrors reth_tpu/evm/interpreter.py's
// latest rule set exactly (EIP-2929 warm/cold, EIP-2200+3529 SSTORE,
// EIP-1153/5656, EIP-7623 floor precomputed by the caller).
//
// Protocol (little-endian):
//   snapshot: u32 n_acct {20B addr, u64 nonce, 32B balance BE, i32 code_id,
//             u8 exists}; u32 n_slot {20B, 32B key, 32B val BE};
//             u32 n_code {u32 len, bytes}
//   env: 20B coinbase, u64 number, u64 timestamp, u64 gas_limit,
//        32B base_fee BE, 32B prevrandao, u64 chain_id, 32B blob_base_fee BE
//   txs: u32 n {u32 index, 20B sender, u8 has_to, 20B to, 32B value BE,
//        u64 gas_limit, 32B eff_gas_price BE, 32B balance_fee_cap BE,
//        u64 intrinsic, u64 floor, u8 tx_type, u32 data_len, data,
//        u32 n_acl {20B, u32 n {32B}}}
//   result per tx: u32 index, u8 status(0 fail,1 ok,2 miss,3 not-run),
//        u8 mode(0 parallel,1 serial), u8 coinbase_sensitive,
//        u64 gas_used, 32B fee_delta BE,
//        u32 out_len, out, u32 n_logs {20B, u8 n_topics {32B}, u32 dlen,
//        data}, u32 n_acct_reads {20B}, u32 n_acct_writes {20B,
//        u8 deleted, u64 nonce, 32B balance BE},
//        u32 n_slot_reads {20B,32B}, u32 n_slot_writes {20B,32B,32B BE}

#include <array>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------- u256
struct U256 {
  uint64_t w[4];  // little-endian limbs
  bool operator==(const U256 &o) const {
    return w[0] == o.w[0] && w[1] == o.w[1] && w[2] == o.w[2] && w[3] == o.w[3];
  }
  bool operator!=(const U256 &o) const { return !(*this == o); }
  bool is_zero() const { return !(w[0] | w[1] | w[2] | w[3]); }
};
static const U256 ZERO = {{0, 0, 0, 0}};

static U256 from_u64(uint64_t v) { return U256{{v, 0, 0, 0}}; }

static U256 from_be(const uint8_t *p, size_t n = 32) {
  U256 r = ZERO;
  for (size_t i = 0; i < n; i++) {
    size_t bit = (n - 1 - i);          // byte significance
    r.w[bit / 8] |= (uint64_t)p[i] << (8 * (bit % 8));
  }
  return r;
}

static void to_be(const U256 &v, uint8_t *p) {
  for (int i = 0; i < 32; i++) {
    int bit = 31 - i;
    p[i] = (uint8_t)(v.w[bit / 8] >> (8 * (bit % 8)));
  }
}

static int cmp(const U256 &a, const U256 &b) {
  for (int i = 3; i >= 0; i--) {
    if (a.w[i] < b.w[i]) return -1;
    if (a.w[i] > b.w[i]) return 1;
  }
  return 0;
}

static U256 add(const U256 &a, const U256 &b) {
  U256 r; unsigned __int128 c = 0;
  for (int i = 0; i < 4; i++) {
    c += (unsigned __int128)a.w[i] + b.w[i];
    r.w[i] = (uint64_t)c; c >>= 64;
  }
  return r;
}

static U256 sub(const U256 &a, const U256 &b) {
  U256 r; __int128 br = 0;
  for (int i = 0; i < 4; i++) {
    __int128 d = (__int128)a.w[i] - b.w[i] - br;
    br = d < 0; if (d < 0) d += ((__int128)1 << 64);
    r.w[i] = (uint64_t)d;
  }
  return r;
}

static U256 mul(const U256 &a, const U256 &b) {
  uint64_t r[8] = {0};
  for (int i = 0; i < 4; i++) {
    unsigned __int128 c = 0;
    for (int j = 0; j + i < 4; j++) {
      c += (unsigned __int128)a.w[i] * b.w[j] + r[i + j];
      r[i + j] = (uint64_t)c; c >>= 64;
    }
  }
  return U256{{r[0], r[1], r[2], r[3]}};
}

static int bitlen(const U256 &a) {
  for (int i = 3; i >= 0; i--)
    if (a.w[i]) return 64 * i + 64 - __builtin_clzll(a.w[i]);
  return 0;
}

static U256 shl_bits(const U256 &a, unsigned s) {
  if (s >= 256) return ZERO;
  U256 r = ZERO; unsigned limb = s / 64, off = s % 64;
  for (int i = 3; i >= 0; i--) {
    uint64_t v = 0;
    if (i >= (int)limb) {
      v = a.w[i - limb] << off;
      if (off && i - (int)limb - 1 >= 0)
        v |= a.w[i - limb - 1] >> (64 - off);
    }
    r.w[i] = v;
  }
  return r;
}

static U256 shr_bits(const U256 &a, unsigned s) {
  if (s >= 256) return ZERO;
  U256 r = ZERO; unsigned limb = s / 64, off = s % 64;
  for (int i = 0; i < 4; i++) {
    uint64_t v = 0;
    if (i + limb < 4) {
      v = a.w[i + limb] >> off;
      if (off && i + limb + 1 < 4) v |= a.w[i + limb + 1] << (64 - off);
    }
    r.w[i] = v;
  }
  return r;
}

// restoring division: returns quotient, sets rem
static U256 divmod(const U256 &a, const U256 &b, U256 &rem) {
  rem = ZERO;
  if (b.is_zero()) { return ZERO; }
  U256 q = ZERO;
  int n = bitlen(a);
  for (int i = n - 1; i >= 0; i--) {
    rem = shl_bits(rem, 1);
    if ((a.w[i / 64] >> (i % 64)) & 1) rem.w[0] |= 1;
    if (cmp(rem, b) >= 0) {
      rem = sub(rem, b);
      q.w[i / 64] |= (uint64_t)1 << (i % 64);
    }
  }
  return q;
}

static bool is_neg(const U256 &a) { return a.w[3] >> 63; }
static U256 neg(const U256 &a) { return sub(ZERO, a); }

// ------------------------------------------------------------- keccak256
static const uint64_t KRC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808aULL,
    0x8000000080008000ULL, 0x000000000000808bULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008aULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000aULL,
    0x000000008000808bULL, 0x800000000000008bULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800aULL, 0x800000008000000aULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL};

static inline uint64_t rotl(uint64_t x, int s) {
  return (x << s) | (x >> (64 - s));
}

static void keccak_f(uint64_t st[25]) {
  for (int round = 0; round < 24; round++) {
    uint64_t bc[5], t;
    for (int i = 0; i < 5; i++)
      bc[i] = st[i] ^ st[i + 5] ^ st[i + 10] ^ st[i + 15] ^ st[i + 20];
    for (int i = 0; i < 5; i++) {
      t = bc[(i + 4) % 5] ^ rotl(bc[(i + 1) % 5], 1);
      for (int j = 0; j < 25; j += 5) st[j + i] ^= t;
    }
    static const int rho[25] = {0,  1,  62, 28, 27, 36, 44, 6,  55, 20, 3, 10, 43,
                                25, 39, 41, 45, 15, 21, 8,  18, 2,  61, 56, 14};
    static const int pi[25] = {0,  10, 20, 5,  15, 16, 1,  11, 21, 6, 7, 17, 2,
                               12, 22, 23, 8,  18, 3,  13, 14, 24, 9, 19, 4};
    uint64_t tmp[25];
    for (int i = 0; i < 25; i++) tmp[pi[i]] = rotl(st[i], rho[i]);
    for (int j = 0; j < 25; j += 5) {
      uint64_t row[5];
      for (int i = 0; i < 5; i++) row[i] = tmp[j + i];
      for (int i = 0; i < 5; i++)
        st[j + i] = row[i] ^ ((~row[(i + 1) % 5]) & row[(i + 2) % 5]);
    }
    st[0] ^= KRC[round];
  }
}

static void keccak256(const uint8_t *data, size_t len, uint8_t out[32]) {
  uint64_t st[25] = {0};
  const size_t rate = 136;
  while (len >= rate) {
    for (size_t i = 0; i < rate / 8; i++) {
      uint64_t v; memcpy(&v, data + 8 * i, 8);
      st[i] ^= v;
    }
    keccak_f(st);
    data += rate; len -= rate;
  }
  uint8_t block[136] = {0};
  memcpy(block, data, len);
  block[len] = 0x01;
  block[135] |= 0x80;
  for (size_t i = 0; i < rate / 8; i++) {
    uint64_t v; memcpy(&v, block + 8 * i, 8);
    st[i] ^= v;
  }
  keccak_f(st);
  for (int i = 0; i < 4; i++) memcpy(out + 8 * i, &st[i], 8);
}

// ------------------------------------------------------------- snapshot
struct Addr {
  uint8_t b[20];
  bool operator<(const Addr &o) const { return memcmp(b, o.b, 20) < 0; }
  bool operator==(const Addr &o) const { return memcmp(b, o.b, 20) == 0; }
};
struct SlotKey {
  Addr a; uint8_t k[32];
  bool operator<(const SlotKey &o) const {
    int c = memcmp(a.b, o.a.b, 20);
    if (c) return c < 0;
    return memcmp(k, o.k, 32) < 0;
  }
};

struct AcctRec { uint64_t nonce; U256 balance; int32_t code_id; bool exists; };

struct Snapshot {
  std::map<Addr, AcctRec> accounts;
  std::map<SlotKey, U256> slots;
  std::vector<std::vector<uint8_t>> codes;
  std::vector<std::vector<uint8_t>> jumpdests;  // bitmap per code
};

// snapshot + writes committed by earlier transactions of this block;
// immutable while a wave's threads read it, mutated only between commits
struct BlockView {
  const Snapshot *snap;
  std::map<Addr, AcctRec> acct_overlay;   // exists=false records deletions
  std::map<SlotKey, U256> slot_overlay;

  const AcctRec *account(const Addr &a, bool &known) const {
    known = true;
    auto it = acct_overlay.find(a);
    if (it != acct_overlay.end()) return it->second.exists ? &it->second : nullptr;
    auto sit = snap->accounts.find(a);
    if (sit == snap->accounts.end()) { known = false; return nullptr; }
    return sit->second.exists ? &sit->second : nullptr;
  }
  bool slot(const SlotKey &k, U256 &out) const {
    auto it = slot_overlay.find(k);
    if (it != slot_overlay.end()) { out = it->second; return true; }
    auto sit = snap->slots.find(k);
    if (sit == snap->slots.end()) return false;
    out = sit->second;
    return true;
  }
};

struct Env {
  Addr coinbase; uint64_t number, timestamp, gas_limit;
  U256 base_fee, prevrandao, blob_base_fee; uint64_t chain_id;
};

struct AclEntry { Addr a; std::vector<std::array<uint8_t, 32>> slots; };
struct Tx {
  uint32_t index; Addr sender; bool has_to; Addr to; U256 value;
  uint64_t nonce, gas_limit; U256 eff_price, fee_cap;
  uint64_t intrinsic, floor; uint8_t tx_type;
  std::vector<uint8_t> data;
  std::vector<AclEntry> acl;
};

struct LogRec { Addr a; std::vector<std::array<uint8_t, 32>> topics; std::vector<uint8_t> data; };
struct AcctWrite { bool deleted; uint64_t nonce; U256 balance; };

struct TxResult {
  uint32_t index = 0;
  uint8_t status = 2;  // miss by default
  bool coinbase_sensitive = false;
  uint64_t gas_used = 0;
  U256 fee_delta = ZERO;
  std::vector<uint8_t> output;
  std::vector<LogRec> logs;
  std::set<Addr> acct_reads;
  std::map<Addr, AcctWrite> acct_writes;
  std::set<SlotKey> slot_reads;
  std::map<SlotKey, U256> slot_writes;
};

// ------------------------------------------------------------- execution
struct Miss {};   // thrown: outside snapshot / unsupported op
struct Halt {};   // exceptional halt: frame consumes all gas

class TxMachine {
 public:
  TxMachine(const BlockView &view, const Env &env, const Tx &tx, TxResult &res)
      : snap_(*view.snap), view_(view), env_(env), tx_(tx), res_(res) {}

  // per-tx mutable state layered over the snapshot
  std::map<Addr, AcctRec> acct_cache_;
  std::set<Addr> acct_dirty_, touched_;
  std::map<SlotKey, U256> slot_cache_, tx_original_;
  std::set<SlotKey> slot_dirty_;
  std::set<Addr> warm_accounts_;
  std::set<SlotKey> warm_slots_;
  std::map<SlotKey, U256> transient_;
  int64_t refund_ = 0;
  std::vector<LogRec> logs_;

  const AcctRec *account(const Addr &a, bool record = true) {
    if (record) {
      if (a == env_.coinbase) res_.coinbase_sensitive = true;
      res_.acct_reads.insert(a);
    }
    auto it = acct_cache_.find(a);
    if (it != acct_cache_.end()) return it->second.exists ? &it->second : nullptr;
    bool known;
    const AcctRec *base = view_.account(a, known);
    if (!known) throw Miss{};  // not preloaded
    AcctRec rec = base ? *base
                       : AcctRec{0, ZERO, -1, false};
    acct_cache_[a] = rec;
    auto &slot = acct_cache_[a];
    return slot.exists ? &slot : nullptr;
  }

  AcctRec &account_mut(const Addr &a) {
    account(a);  // populate cache (+ read record)
    acct_dirty_.insert(a);
    auto &rec = acct_cache_[a];
    if (!rec.exists) { rec.exists = true; rec.nonce = 0; rec.balance = ZERO; rec.code_id = -1; }
    return rec;
  }

  U256 balance_of(const Addr &a) {
    const AcctRec *r = account(a);
    return r ? r->balance : ZERO;
  }

  const std::vector<uint8_t> *code_of(const Addr &a) {
    const AcctRec *r = account(a);
    if (!r || r->code_id < 0) return nullptr;
    return &snap_.codes[r->code_id];
  }

  U256 sload(const Addr &a, const uint8_t k[32]) {
    SlotKey key{a, {}}; memcpy(key.k, k, 32);
    res_.slot_reads.insert(key);
    auto it = slot_cache_.find(key);
    if (it != slot_cache_.end()) return it->second;
    U256 v;
    if (!view_.slot(key, v)) throw Miss{};
    slot_cache_[key] = v;
    return v;
  }

  U256 original(const Addr &a, const uint8_t k[32]) {
    SlotKey key{a, {}}; memcpy(key.k, k, 32);
    auto it = tx_original_.find(key);
    if (it != tx_original_.end()) return it->second;
    return sload(a, k);
  }

  void sstore_val(const Addr &a, const uint8_t k[32], const U256 &v) {
    SlotKey key{a, {}}; memcpy(key.k, k, 32);
    U256 prev = sload(a, k);
    tx_original_.emplace(key, prev);
    slot_cache_[key] = v;
    slot_dirty_.insert(key);
  }

  bool warm_account(const Addr &a) {
    if (warm_accounts_.count(a)) return true;
    warm_accounts_.insert(a);
    return false;
  }
  bool warm_slot(const Addr &a, const uint8_t k[32]) {
    SlotKey key{a, {}}; memcpy(key.k, k, 32);
    if (warm_slots_.count(key)) return true;
    warm_slots_.insert(key);
    return false;
  }

  // gas constants mirroring evm/interpreter.py (latest rules)
  static const uint64_t G_WARM = 100, G_COLD_ACCT = 2600, G_COLD_SLOAD = 2100;
  static const uint64_t G_SSTORE_SET = 20000, G_SSTORE_RESET = 2900, R_CLEAR = 4800;

  bool run() {
    const Tx &tx = tx_;
    // validity (mirrors _execute_tx; failures => MISS so Python reproduces
    // the exact error on its serial retry path)
    const AcctRec *snd = account(tx.sender);
    uint64_t snd_nonce = snd ? snd->nonce : 0;
    U256 snd_bal = snd ? snd->balance : ZERO;
    if (snd && snd->code_id >= 0) throw Miss{};  // EIP-3607/7702 — python
    if (snd_nonce != tx.nonce) throw Miss{};  // python reproduces the error
    U256 max_cost = add(mul(from_u64(tx.gas_limit), tx.fee_cap), tx.value);
    if (cmp(snd_bal, max_cost) < 0) throw Miss{};
    if (tx.gas_limit < tx.intrinsic) throw Miss{};

    // buy gas + nonce
    AcctRec &s = account_mut(tx.sender);
    s.balance = sub(s.balance, mul(from_u64(tx.gas_limit), tx.eff_price));
    s.nonce += 1;
    touched_.insert(tx.sender);

    // warm init (EIP-2929 + 3651 + 7702 precompile range 1..17)
    warm_account(tx.sender);
    warm_account(env_.coinbase);
    for (int i = 1; i <= 17; i++) {
      Addr p{}; p.b[19] = (uint8_t)i;
      warm_accounts_.insert(p);
    }
    if (tx.has_to) warm_account(tx.to);
    for (const auto &e : tx.acl) {
      warm_accounts_.insert(e.a);
      for (const auto &sl : e.slots) {
        SlotKey key{e.a, {}}; memcpy(key.k, sl.data(), 32);
        warm_slots_.insert(key);
      }
    }

    if (!tx.has_to) throw Miss{};  // creation tx: python path
    // precompile target: python path
    bool zero19 = true;
    for (int i = 0; i < 19; i++) if (tx.to.b[i]) { zero19 = false; break; }
    if (zero19 && tx.to.b[19] >= 1 && tx.to.b[19] <= 17) throw Miss{};

    const AcctRec *to_rec = account(tx.to);
    const std::vector<uint8_t> *code =
        (to_rec && to_rec->code_id >= 0) ? &snap_.codes[to_rec->code_id]
                                         : nullptr;
    int32_t code_id = to_rec ? to_rec->code_id : -1;
    if (code && code->size() >= 3 && (*code)[0] == 0xEF && (*code)[1] == 0x01)
      throw Miss{};  // 7702 delegation designator — python path

    uint64_t gas = tx.gas_limit - tx.intrinsic;
    bool success = true;
    // value transfer
    if (!tx.value.is_zero()) {
      // balance re-check after gas purchase (matches _call_gen prologue)
      if (cmp(balance_of(tx.sender), tx.value) < 0) {
        success = false; gas = tx.gas_limit;  // top-level halt burns frame gas
        // matches python: _call_gen returns (False, frame.gas, b"") -> the
        // frame keeps its gas; gas_used = intrinsic only
        gas = tx.gas_limit - tx.intrinsic;
      } else {
        AcctRec &a = account_mut(tx.sender);
        a.balance = sub(a.balance, tx.value);
        AcctRec &b = account_mut(tx.to);
        b.balance = add(b.balance, tx.value);
        touched_.insert(tx.to);
      }
    }
    uint64_t gas_left = gas;
    if (success && code) {
      // snapshot for revert/halt: copy caches (txs are small; fine)
      auto save_acct = acct_cache_; auto save_dirty = acct_dirty_;
      auto save_touch = touched_;
      auto save_slots = slot_cache_; auto save_sdirty = slot_dirty_;
      auto save_orig = tx_original_; auto save_ref = refund_;
      auto save_logs = logs_.size();
      try {
        gas_left = interpret(*code, snap_.jumpdests[code_id], tx.to, gas);
      } catch (Halt &) {
        acct_cache_ = save_acct; acct_dirty_ = save_dirty;
        touched_ = save_touch;
        slot_cache_ = save_slots; slot_dirty_ = save_sdirty;
        tx_original_ = save_orig; refund_ = save_ref;
        logs_.resize(save_logs);
        success = false; gas_left = 0;
        res_.output.clear();
      } catch (RevertExc &r) {
        acct_cache_ = save_acct; acct_dirty_ = save_dirty;
        touched_ = save_touch;
        slot_cache_ = save_slots; slot_dirty_ = save_sdirty;
        tx_original_ = save_orig; refund_ = save_ref;
        logs_.resize(save_logs);
        success = false; gas_left = r.gas_left;
        res_.output = std::move(r.output);
      }
    }
    uint64_t gas_used = tx.gas_limit - gas_left;
    if (success) {
      uint64_t cap = gas_used / 5;  // EIP-3529
      uint64_t refund = refund_ > 0 ? (uint64_t)refund_ : 0;
      if (refund > cap) refund = cap;
      gas_used -= refund;
    }
    if (gas_used < tx.floor) gas_used = tx.floor;  // EIP-7623
    // refund unused gas; priority fee as a commutative delta
    AcctRec &fs = account_mut(tx.sender);
    fs.balance = add(fs.balance, mul(from_u64(tx.gas_limit - gas_used), tx.eff_price));
    U256 priority = cmp(tx.eff_price, env_.base_fee) > 0
                        ? sub(tx.eff_price, env_.base_fee) : ZERO;
    res_.fee_delta = mul(from_u64(gas_used), priority);
    // EIP-161 touched-empty deletion
    for (const Addr &a : touched_) {
      auto it = acct_cache_.find(a);
      if (it != acct_cache_.end() && it->second.exists &&
          it->second.nonce == 0 && it->second.balance.is_zero() &&
          it->second.code_id < 0) {
        it->second.exists = false;
        acct_dirty_.insert(a);
      }
    }
    res_.gas_used = gas_used;
    res_.status = success ? 1 : 0;
    res_.logs = std::move(logs_);
    for (const Addr &a : acct_dirty_) {
      const AcctRec &r = acct_cache_[a];
      res_.acct_writes[a] = AcctWrite{!r.exists, r.nonce, r.balance};
    }
    for (const SlotKey &k : slot_dirty_) res_.slot_writes[k] = slot_cache_[k];
    return true;
  }

 private:
  struct RevertExc { uint64_t gas_left; std::vector<uint8_t> output; };

  const Snapshot &snap_;
  const BlockView &view_;
  const Env &env_;
  const Tx &tx_;
  TxResult &res_;

  // one top-level frame (CALL/CREATE -> Miss)
  uint64_t interpret(const std::vector<uint8_t> &code,
                     const std::vector<uint8_t> &jd, const Addr &self,
                     uint64_t gas) {
    std::vector<U256> stack;
    stack.reserve(64);
    std::vector<uint8_t> mem;
    size_t pc = 0;
    const size_t n = code.size();

    auto use = [&](uint64_t amt) {
      if (gas < amt) throw Halt{};
      gas -= amt;
    };
    auto pop = [&]() -> U256 {
      if (stack.empty()) throw Halt{};
      U256 v = stack.back(); stack.pop_back(); return v;
    };
    auto push = [&](const U256 &v) {
      if (stack.size() >= 1024) throw Halt{};
      stack.push_back(v);
    };
    auto mem_expand = [&](uint64_t off, uint64_t size) {
      if (size == 0) return;
      uint64_t end = off + size;
      if (end > mem.size()) {
        uint64_t nw = (end + 31) / 32, ow = (mem.size() + 31) / 32;
        uint64_t cost = (3 * nw + nw * nw / 512) - (3 * ow + ow * ow / 512);
        use(cost);
        mem.resize(nw * 32, 0);
      }
    };
    auto check_off = [&](const U256 &v) -> uint64_t {
      // matches python: offsets/sizes above 2^32 halt when touched
      if (v.w[1] | v.w[2] | v.w[3] || v.w[0] > (1ULL << 32)) throw Halt{};
      return v.w[0];
    };

    while (pc < n) {
      uint8_t op = code[pc];
      pc++;
      if (op >= 0x5F && op <= 0x7F) {  // PUSH0..32
        unsigned len = op - 0x5F;
        use(len == 0 ? 2 : 3);
        if (stack.size() >= 1024) throw Halt{};
        U256 v = ZERO;
        if (len) {
          uint8_t buf[32] = {0};
          size_t avail = pc < n ? (n - pc < len ? n - pc : len) : 0;
          // truncated PUSH zero-pads on the RIGHT (execution-specs
          // buffer_read): the len-byte window starts at buf[32-len]
          memcpy(buf + (32 - len), code.data() + pc, avail);
          v = from_be(buf);
          pc += len;
        }
        push(v);
        continue;
      }
      if (op >= 0x80 && op <= 0x8F) {  // DUP
        use(3);
        unsigned i = op - 0x7F;
        if (stack.size() < i || stack.size() >= 1024) throw Halt{};
        stack.push_back(stack[stack.size() - i]);
        continue;
      }
      if (op >= 0x90 && op <= 0x9F) {  // SWAP
        use(3);
        unsigned i = op - 0x8F;
        if (stack.size() < i + 1) throw Halt{};
        std::swap(stack[stack.size() - 1], stack[stack.size() - 1 - i]);
        continue;
      }
      switch (op) {
        case 0x5B: use(1); break;  // JUMPDEST
        case 0x57: {  // JUMPI
          use(10);
          U256 dest = pop(), cond = pop();
          if (!cond.is_zero()) {
            if (dest.w[1] | dest.w[2] | dest.w[3] || dest.w[0] >= n ||
                !(jd[dest.w[0] / 8] & (1 << (dest.w[0] % 8))))
              throw Halt{};
            pc = dest.w[0];
          }
          break;
        }
        case 0x56: {  // JUMP
          use(8);
          U256 dest = pop();
          if (dest.w[1] | dest.w[2] | dest.w[3] || dest.w[0] >= n ||
              !(jd[dest.w[0] / 8] & (1 << (dest.w[0] % 8))))
            throw Halt{};
          pc = dest.w[0];
          break;
        }
        case 0x01: { use(3); U256 a = pop(), b = pop(); push(add(a, b)); break; }
        case 0x03: { use(3); U256 a = pop(), b = pop(); push(sub(a, b)); break; }
        case 0x02: { use(5); U256 a = pop(), b = pop(); push(mul(a, b)); break; }
        case 0x04: { use(5); U256 a = pop(), b = pop(); U256 r;
          push(b.is_zero() ? ZERO : divmod(a, b, r)); break; }
        case 0x06: { use(5); U256 a = pop(), b = pop(); U256 r;
          if (b.is_zero()) push(ZERO); else { divmod(a, b, r); push(r); } break; }
        case 0x05: {  // SDIV
          use(5); U256 a = pop(), b = pop();
          if (b.is_zero()) { push(ZERO); break; }
          bool na = is_neg(a), nb = is_neg(b);
          U256 ua = na ? neg(a) : a, ub = nb ? neg(b) : b, r;
          U256 q = divmod(ua, ub, r);
          push(na == nb ? q : neg(q));
          break;
        }
        case 0x07: {  // SMOD
          use(5); U256 a = pop(), b = pop();
          if (b.is_zero()) { push(ZERO); break; }
          bool na = is_neg(a);
          U256 ua = na ? neg(a) : a, ub = is_neg(b) ? neg(b) : b, r;
          divmod(ua, ub, r);
          push(na ? neg(r) : r);
          break;
        }
        case 0x08: case 0x09: {  // ADDMOD / MULMOD — python path (512-bit)
          throw Miss{};
        }
        case 0x0A: {  // EXP
          U256 a = pop(), e = pop();
          use(10 + 50 * (uint64_t)((bitlen(e) + 7) / 8));
          U256 r = from_u64(1), base = a, ex = e;
          while (!ex.is_zero()) {
            if (ex.w[0] & 1) r = mul(r, base);
            base = mul(base, base);
            ex = shr_bits(ex, 1);
          }
          push(r);
          break;
        }
        case 0x0B: {  // SIGNEXTEND
          use(5); U256 b = pop(), x = pop();
          if (b.w[1] | b.w[2] | b.w[3] || b.w[0] >= 31) { push(x); break; }
          unsigned bit = 8 * (b.w[0] + 1) - 1;
          bool set = (x.w[bit / 64] >> (bit % 64)) & 1;
          U256 maskv = shl_bits(U256{{~0ULL, ~0ULL, ~0ULL, ~0ULL}}, bit + 1);
          U256 r;
          for (int i = 0; i < 4; i++)
            r.w[i] = set ? (x.w[i] | maskv.w[i]) : (x.w[i] & ~maskv.w[i]);
          push(r);
          break;
        }
        case 0x10: { use(3); U256 a = pop(), b = pop(); push(from_u64(cmp(a, b) < 0)); break; }
        case 0x11: { use(3); U256 a = pop(), b = pop(); push(from_u64(cmp(a, b) > 0)); break; }
        case 0x12: {  // SLT
          use(3); U256 a = pop(), b = pop();
          bool na = is_neg(a), nb = is_neg(b);
          bool r = na != nb ? na : cmp(a, b) < 0;
          push(from_u64(r)); break;
        }
        case 0x13: {  // SGT
          use(3); U256 a = pop(), b = pop();
          bool na = is_neg(a), nb = is_neg(b);
          bool r = na != nb ? nb : cmp(a, b) > 0;
          push(from_u64(r)); break;
        }
        case 0x14: { use(3); U256 a = pop(), b = pop(); push(from_u64(a == b)); break; }
        case 0x15: { use(3); push(from_u64(pop().is_zero())); break; }
        case 0x16: { use(3); U256 a = pop(), b = pop(); U256 r;
          for (int i=0;i<4;i++) r.w[i]=a.w[i]&b.w[i]; push(r); break; }
        case 0x17: { use(3); U256 a = pop(), b = pop(); U256 r;
          for (int i=0;i<4;i++) r.w[i]=a.w[i]|b.w[i]; push(r); break; }
        case 0x18: { use(3); U256 a = pop(), b = pop(); U256 r;
          for (int i=0;i<4;i++) r.w[i]=a.w[i]^b.w[i]; push(r); break; }
        case 0x19: { use(3); U256 a = pop(); U256 r;
          for (int i=0;i<4;i++) r.w[i]=~a.w[i]; push(r); break; }
        case 0x1A: {  // BYTE
          use(3); U256 i = pop(), x = pop();
          if (i.w[1] | i.w[2] | i.w[3] || i.w[0] >= 32) { push(ZERO); break; }
          unsigned bit = 8 * (31 - i.w[0]);
          push(from_u64((x.w[bit / 64] >> (bit % 64)) & 0xFF));
          break;
        }
        case 0x1B: { use(3); U256 s = pop(), x = pop();
          push(s.w[1]|s.w[2]|s.w[3]||s.w[0]>=256 ? ZERO : shl_bits(x, s.w[0])); break; }
        case 0x1C: { use(3); U256 s = pop(), x = pop();
          push(s.w[1]|s.w[2]|s.w[3]||s.w[0]>=256 ? ZERO : shr_bits(x, s.w[0])); break; }
        case 0x1D: {  // SAR
          use(3); U256 s = pop(), x = pop();
          bool nx = is_neg(x);
          if (s.w[1]|s.w[2]|s.w[3]||s.w[0] >= 256) {
            push(nx ? U256{{~0ULL,~0ULL,~0ULL,~0ULL}} : ZERO); break;
          }
          U256 r = shr_bits(x, s.w[0]);
          if (nx && s.w[0]) {
            U256 maskv = shl_bits(U256{{~0ULL,~0ULL,~0ULL,~0ULL}}, 256 - s.w[0]);
            for (int i=0;i<4;i++) r.w[i] |= maskv.w[i];
          }
          push(r);
          break;
        }
        case 0x20: {  // KECCAK256
          U256 off = pop(), size = pop();
          uint64_t sz = check_off(size);
          use(30 + 6 * ((sz + 31) / 32));
          uint64_t o = 0;
          if (sz) { o = check_off(off); mem_expand(o, sz); }
          uint8_t h[32];
          static const uint8_t kdummy = 0;
          keccak256(sz ? mem.data() + o : &kdummy, sz, h);
          push(from_be(h));
          break;
        }
        case 0x30: { use(2); push(addr_word(self)); break; }
        case 0x31: {  // BALANCE
          U256 a = pop(); Addr ad = word_addr(a);
          use(warm_account(ad) ? G_WARM : G_COLD_ACCT);
          push(balance_of(ad));
          break;
        }
        case 0x32: { use(2); push(addr_word(tx_.sender)); break; }  // ORIGIN
        case 0x33: { use(2); push(addr_word(tx_.sender)); break; }  // CALLER (top frame)
        case 0x34: { use(2); push(tx_.value); break; }
        case 0x35: {  // CALLDATALOAD
          use(3); U256 iv = pop();
          if (iv.w[1]|iv.w[2]|iv.w[3] || iv.w[0] >= tx_.data.size()) { push(ZERO); break; }
          uint8_t buf[32] = {0};
          size_t i = iv.w[0];
          size_t avail = tx_.data.size() - i < 32 ? tx_.data.size() - i : 32;
          memcpy(buf, tx_.data.data() + i, avail);
          push(from_be(buf));
          break;
        }
        case 0x36: { use(2); push(from_u64(tx_.data.size())); break; }
        case 0x37: {  // CALLDATACOPY
          U256 d = pop(), s = pop(), size = pop();
          uint64_t sz = check_off(size);
          use(3 + 3 * ((sz + 31) / 32));
          if (sz == 0) break;
          uint64_t dd = check_off(d);
          mem_expand(dd, sz);
          // clamp ss >= data.size() to zero-fill: `ss + i` wraps uint64
          // for src offsets near 2^64 and would read real calldata
          uint64_t ss = s.w[1]|s.w[2]|s.w[3] ? ~0ULL : s.w[0];
          uint64_t avail =
              ss < tx_.data.size() ? tx_.data.size() - ss : 0;
          for (uint64_t i = 0; i < sz; i++)
            mem[dd + i] = i < avail ? tx_.data[ss + i] : 0;
          break;
        }
        case 0x38: { use(2); push(from_u64(n)); break; }
        case 0x39: {  // CODECOPY
          U256 d = pop(), s = pop(), size = pop();
          uint64_t sz = check_off(size);
          use(3 + 3 * ((sz + 31) / 32));
          if (sz == 0) break;
          uint64_t dd = check_off(d);
          mem_expand(dd, sz);
          // same uint64 `ss + i` wrap clamp as CALLDATACOPY above
          uint64_t ss = s.w[1]|s.w[2]|s.w[3] ? ~0ULL : s.w[0];
          uint64_t avail = ss < n ? n - ss : 0;
          for (uint64_t i = 0; i < sz; i++)
            mem[dd + i] = i < avail ? code[ss + i] : 0;
          break;
        }
        case 0x3A: { use(2); push(tx_.eff_price); break; }
        case 0x3B: {  // EXTCODESIZE
          U256 a = pop(); Addr ad = word_addr(a);
          use(warm_account(ad) ? G_WARM : G_COLD_ACCT);
          const std::vector<uint8_t> *c = code_of(ad);
          push(from_u64(c ? c->size() : 0));
          break;
        }
        case 0x3D: { use(2); push(from_u64(retdata_.size())); break; }
        case 0x3E: {  // RETURNDATACOPY
          U256 d = pop(), s = pop(), size = pop();
          uint64_t sz = check_off(size);
          use(3 + 3 * ((sz + 31) / 32));
          uint64_t ss = s.w[1]|s.w[2]|s.w[3] ? ~0ULL : s.w[0];
          if (ss == ~0ULL || ss + sz > retdata_.size()) throw Halt{};
          if (sz == 0) break;
          uint64_t dd = check_off(d);
          mem_expand(dd, sz);
          memcpy(mem.data() + dd, retdata_.data() + ss, sz);
          break;
        }
        case 0x3F: {  // EXTCODEHASH
          U256 a = pop(); Addr ad = word_addr(a);
          use(warm_account(ad) ? G_WARM : G_COLD_ACCT);
          const AcctRec *r = account(ad);
          if (!r || (r->nonce == 0 && r->balance.is_zero() && r->code_id < 0)) {
            push(ZERO);
          } else if (r->code_id < 0) {
            static const uint8_t kempty[32] = {
                0xc5,0xd2,0x46,0x01,0x86,0xf7,0x23,0x3c,0x92,0x7e,0x7d,0xb2,
                0xdc,0xc7,0x03,0xc0,0xe5,0x00,0xb6,0x53,0xca,0x82,0x27,0x3b,
                0x7b,0xfa,0xd8,0x04,0x5d,0x85,0xa4,0x70};
            push(from_be(kempty));
          } else {
            const auto &c = snap_.codes[r->code_id];
            uint8_t h[32]; keccak256(c.data(), c.size(), h);
            push(from_be(h));
          }
          break;
        }
        case 0x41: { use(2); push(addr_word(env_.coinbase)); break; }
        case 0x42: { use(2); push(from_u64(env_.timestamp)); break; }
        case 0x43: { use(2); push(from_u64(env_.number)); break; }
        case 0x44: { use(2); push(env_.prevrandao); break; }
        case 0x45: { use(2); push(from_u64(env_.gas_limit)); break; }
        case 0x46: { use(2); push(from_u64(env_.chain_id)); break; }
        case 0x47: { use(5); push(balance_of(self)); break; }
        case 0x48: { use(2); push(env_.base_fee); break; }
        case 0x49: { use(3); pop(); push(ZERO); break; }  // BLOBHASH (no blobs natively)
        case 0x4A: { use(2); push(env_.blob_base_fee); break; }
        case 0x50: { use(2); pop(); break; }
        case 0x51: {  // MLOAD
          use(3); uint64_t o = check_off(pop());
          mem_expand(o, 32);
          push(from_be(mem.data() + o));
          break;
        }
        case 0x52: {  // MSTORE
          use(3); U256 offv = pop(), v = pop();
          uint64_t o = check_off(offv);
          mem_expand(o, 32);
          to_be(v, mem.data() + o);
          break;
        }
        case 0x53: {  // MSTORE8
          use(3); U256 offv = pop(), v = pop();
          uint64_t o = check_off(offv);
          mem_expand(o, 1);
          mem[o] = (uint8_t)v.w[0];
          break;
        }
        case 0x54: {  // SLOAD
          U256 kv = pop();
          uint8_t k[32]; to_be(kv, k);
          use(warm_slot(self, k) ? G_WARM : G_COLD_SLOAD);
          push(sload(self, k));
          break;
        }
        case 0x55: {  // SSTORE (EIP-2200 + 2929 + 3529)
          if (gas <= 2300) throw Halt{};
          U256 kv = pop(), v = pop();
          uint8_t k[32]; to_be(kv, k);
          uint64_t cold = warm_slot(self, k) ? 0 : G_COLD_SLOAD;
          U256 cur = sload(self, k);
          U256 orig = original(self, k);
          uint64_t cost;
          if (v == cur) cost = cold + G_WARM;
          else if (cur == orig)
            cost = cold + (orig.is_zero() ? G_SSTORE_SET : G_SSTORE_RESET);
          else cost = cold + G_WARM;
          use(cost);
          if (v != cur) {
            if (cur == orig) {
              if (!orig.is_zero() && v.is_zero()) refund_ += R_CLEAR;
            } else {
              if (!orig.is_zero()) {
                if (cur.is_zero()) refund_ -= R_CLEAR;
                else if (v.is_zero()) refund_ += R_CLEAR;
              }
              if (v == orig)
                refund_ += orig.is_zero() ? (int64_t)(G_SSTORE_SET - G_WARM)
                                          : (int64_t)(G_SSTORE_RESET - G_WARM);
            }
            sstore_val(self, k, v);
          }
          break;
        }
        case 0x58: { use(2); push(from_u64(pc - 1)); break; }
        case 0x59: { use(2); push(from_u64(mem.size())); break; }
        case 0x5A: { use(2); push(from_u64(gas)); break; }
        case 0x5C: {  // TLOAD
          use(100); U256 kv = pop();
          SlotKey key{self, {}}; to_be(kv, key.k);
          auto it = transient_.find(key);
          push(it == transient_.end() ? ZERO : it->second);
          break;
        }
        case 0x5D: {  // TSTORE
          use(100); U256 kv = pop(), v = pop();
          SlotKey key{self, {}}; to_be(kv, key.k);
          transient_[key] = v;
          break;
        }
        case 0x5E: {  // MCOPY
          U256 d = pop(), s = pop(), size = pop();
          uint64_t sz = check_off(size);
          use(3 + 3 * ((sz + 31) / 32));
          if (sz == 0) break;
          uint64_t ss = check_off(s), dd = check_off(d);
          mem_expand(ss, sz);
          std::vector<uint8_t> tmp(mem.begin() + ss, mem.begin() + ss + sz);
          mem_expand(dd, sz);
          memcpy(mem.data() + dd, tmp.data(), sz);
          break;
        }
        case 0xA0: case 0xA1: case 0xA2: case 0xA3: case 0xA4: {  // LOG
          unsigned nt = op - 0xA0;
          U256 off = pop(), size = pop();
          LogRec log; log.a = self;
          for (unsigned i = 0; i < nt; i++) {
            std::array<uint8_t, 32> t;
            to_be(pop(), t.data());
            log.topics.push_back(t);
          }
          uint64_t sz = check_off(size);
          use(375 + 375ULL * nt + 8 * sz);
          if (sz) {
            uint64_t o = check_off(off);
            mem_expand(o, sz);
            log.data.assign(mem.begin() + o, mem.begin() + o + sz);
          }
          logs_.push_back(std::move(log));
          break;
        }
        case 0x00: return gas;  // STOP
        case 0xF3: {  // RETURN
          U256 off = pop(), size = pop();
          uint64_t sz = check_off(size);
          if (sz) {  // zero size ignores the offset (python mem_read)
            uint64_t o = check_off(off);
            mem_expand(o, sz);
            res_.output.assign(mem.begin() + o, mem.begin() + o + sz);
          }
          return gas;
        }
        case 0xFD: {  // REVERT
          U256 off = pop(), size = pop();
          uint64_t sz = check_off(size);
          RevertExc r; r.gas_left = gas;
          if (sz) {
            uint64_t o = check_off(off);
            mem_expand(o, sz);
            r.output.assign(mem.begin() + o, mem.begin() + o + sz);
          }
          throw r;
        }
        case 0xFE: throw Halt{};
        // everything with sub-frames or exotic host needs: python path
        default:
          if (op == 0x3C || op == 0x40 ||  // EXTCODECOPY/BLOCKHASH
              op == 0xF0 || op == 0xF1 || op == 0xF2 || op == 0xF4 ||
              op == 0xF5 || op == 0xFA || op == 0xFF)
            throw Miss{};
          throw Halt{};  // unassigned opcode
      }
    }
    return gas;
  }

  static U256 addr_word(const Addr &a) {
    uint8_t buf[32] = {0};
    memcpy(buf + 12, a.b, 20);
    return from_be(buf);
  }
  static Addr word_addr(const U256 &v) {
    uint8_t buf[32]; to_be(v, buf);
    Addr a; memcpy(a.b, buf + 12, 20);
    return a;
  }

  std::vector<uint8_t> retdata_;
};

// ------------------------------------------------------------- (de)marshal
struct Reader {
  const uint8_t *p; size_t left;
  void need(size_t n) { if (left < n) abort(); }
  uint32_t u32() { need(4); uint32_t v; memcpy(&v, p, 4); p += 4; left -= 4; return v; }
  uint64_t u64() { need(8); uint64_t v; memcpy(&v, p, 8); p += 8; left -= 8; return v; }
  uint8_t u8() { need(1); return left--, *p++; }
  void bytes(void *dst, size_t n) { need(n); memcpy(dst, p, n); p += n; left -= n; }
};

struct Writer {
  std::vector<uint8_t> buf;
  void u32(uint32_t v) { append(&v, 4); }
  void u64(uint64_t v) { append(&v, 8); }
  void u8(uint8_t v) { buf.push_back(v); }
  void append(const void *src, size_t n) {
    const uint8_t *s = (const uint8_t *)src;
    buf.insert(buf.end(), s, s + n);
  }
};

}  // namespace

namespace {
bool intersects_accts(const std::set<Addr> &committed, const TxResult &r) {
  for (const Addr &a : r.acct_reads)
    if (committed.count(a)) return true;
  for (const auto &kv : r.acct_writes)
    if (committed.count(kv.first)) return true;
  return false;
}
bool intersects_slots(const std::set<SlotKey> &committed, const TxResult &r) {
  for (const SlotKey &k : r.slot_reads)
    if (committed.count(k)) return true;
  for (const auto &kv : r.slot_writes)
    if (committed.count(kv.first)) return true;
  return false;
}
}  // namespace

extern "C" {

// Execute a SEGMENT of a block: txs partitioned into in-order waves; each
// wave speculates on threads, commits in order with actual-access
// validation (conflicts re-run serially against the merged view), and the
// merged writes feed the next wave — the whole BAL engine loop with the
// GIL nowhere in sight. Stops at the first transaction the native core
// cannot take (status=2); later txs report status=3 (not run) and Python
// resumes from there. Returns malloc'd result buffer (evm_free).
uint8_t *evm_execute_block(const uint8_t *snap_buf, uint64_t snap_len,
                           const uint8_t *env_buf, uint64_t env_len,
                           const uint8_t *txs_buf, uint64_t txs_len,
                           const uint8_t *waves_buf, uint64_t waves_len,
                           uint64_t remaining_gas, int n_threads,
                           uint64_t *out_len) {
  Snapshot snap;
  {
    Reader r{snap_buf, (size_t)snap_len};
    uint32_t na = r.u32();
    for (uint32_t i = 0; i < na; i++) {
      Addr a; r.bytes(a.b, 20);
      AcctRec rec;
      rec.nonce = r.u64();
      uint8_t bal[32]; r.bytes(bal, 32); rec.balance = from_be(bal);
      uint32_t cid = r.u32(); rec.code_id = (int32_t)cid;
      rec.exists = r.u8();
      snap.accounts[a] = rec;
    }
    uint32_t ns = r.u32();
    for (uint32_t i = 0; i < ns; i++) {
      SlotKey k; r.bytes(k.a.b, 20); r.bytes(k.k, 32);
      uint8_t v[32]; r.bytes(v, 32);
      snap.slots[k] = from_be(v);
    }
    uint32_t nc = r.u32();
    for (uint32_t i = 0; i < nc; i++) {
      uint32_t len = r.u32();
      std::vector<uint8_t> code(len);
      r.bytes(code.data(), len);
      // jumpdest analysis up front: per-code, shared read-only by every
      // thread for the whole call (no caches keyed on heap addresses)
      std::vector<uint8_t> bm((code.size() + 7) / 8, 0);
      for (size_t j = 0; j < code.size();) {
        uint8_t op = code[j];
        if (op == 0x5B) bm[j / 8] |= 1 << (j % 8);
        j += (op >= 0x60 && op <= 0x7F) ? (op - 0x5F + 1) : 1;
      }
      snap.codes.push_back(std::move(code));
      snap.jumpdests.push_back(std::move(bm));
    }
  }
  Env env;
  {
    Reader r{env_buf, (size_t)env_len};
    r.bytes(env.coinbase.b, 20);
    env.number = r.u64(); env.timestamp = r.u64(); env.gas_limit = r.u64();
    uint8_t b[32];
    r.bytes(b, 32); env.base_fee = from_be(b);
    r.bytes(b, 32); env.prevrandao = from_be(b);
    env.chain_id = r.u64();
    r.bytes(b, 32); env.blob_base_fee = from_be(b);
  }
  std::vector<Tx> txs;
  {
    Reader r{txs_buf, (size_t)txs_len};
    uint32_t nt = r.u32();
    for (uint32_t i = 0; i < nt; i++) {
      Tx t;
      t.index = r.u32();
      r.bytes(t.sender.b, 20);
      t.has_to = r.u8();
      r.bytes(t.to.b, 20);
      uint8_t b[32];
      r.bytes(b, 32); t.value = from_be(b);
      t.nonce = r.u64();
      t.gas_limit = r.u64();
      r.bytes(b, 32); t.eff_price = from_be(b);
      r.bytes(b, 32); t.fee_cap = from_be(b);
      t.intrinsic = r.u64(); t.floor = r.u64();
      t.tx_type = r.u8();
      uint32_t dl = r.u32();
      t.data.resize(dl); r.bytes(t.data.data(), dl);
      uint32_t nacl = r.u32();
      for (uint32_t j = 0; j < nacl; j++) {
        AclEntry e; r.bytes(e.a.b, 20);
        uint32_t nsl = r.u32();
        for (uint32_t k = 0; k < nsl; k++) {
          std::array<uint8_t, 32> sl; r.bytes(sl.data(), 32);
          e.slots.push_back(sl);
        }
        t.acl.push_back(std::move(e));
      }
      txs.push_back(std::move(t));
    }
  }

  std::vector<uint32_t> wave_sizes;
  {
    Reader r{waves_buf, (size_t)waves_len};
    uint32_t nw = r.u32();
    for (uint32_t i = 0; i < nw; i++) wave_sizes.push_back(r.u32());
  }

  BlockView view; view.snap = &snap;
  std::vector<TxResult> results(txs.size());
  std::vector<uint8_t> exec_mode(txs.size(), 0);  // 0 parallel, 1 serial
  uint64_t cumulative = 0;
  bool stopped = false;

  // hand a tx back to Python keeping the reads it managed before failing:
  // the optimistic scheduler diffs them against its snapshot to decide
  // which keys the async storage layer must prefetch before the retry
  auto demote = [&](size_t i, uint8_t status) {
    TxResult keep;
    keep.index = txs[i].index;
    keep.status = status;
    keep.coinbase_sensitive = results[i].coinbase_sensitive;
    keep.acct_reads = std::move(results[i].acct_reads);
    keep.slot_reads = std::move(results[i].slot_reads);
    results[i] = std::move(keep);
  };

  auto speculate = [&](size_t i, TxResult &res) {
    res = TxResult{};
    res.index = txs[i].index;
    try {
      TxMachine m(view, env, txs[i], res);
      m.run();
    } catch (...) {
      std::set<Addr> reads = std::move(res.acct_reads);
      std::set<SlotKey> sreads = std::move(res.slot_reads);
      res = TxResult{};
      res.index = txs[i].index;
      res.status = 2;
      res.acct_reads = std::move(reads);   // partial reads still conflict-
      res.slot_reads = std::move(sreads);  // relevant for the retry decision
    }
  };

  // persistent worker pool: one spawn for the whole call, waves hand out
  // work through an atomic cursor (thread-per-wave spawning measurably
  // dominated execution for small transactions)
  struct Pool {
    std::mutex m;
    std::condition_variable cv_work, cv_done;
    size_t lo = 0, hi = 0;
    std::atomic<size_t> cursor{0};
    std::atomic<size_t> pending{0};
    uint64_t epoch = 0;
    bool quit = false;
  } pool_state;
  size_t nthreads = n_threads > 1 ? (size_t)n_threads : 0;
  std::vector<std::thread> workers;
  if (nthreads > 1 && txs.size() >= 16) {
    for (size_t t = 0; t < nthreads; t++) {
      workers.emplace_back([&]() {
        uint64_t seen = 0;
        for (;;) {
          {
            std::unique_lock<std::mutex> lk(pool_state.m);
            pool_state.cv_work.wait(lk, [&] {
              return pool_state.quit || pool_state.epoch != seen;
            });
            if (pool_state.quit) return;
            seen = pool_state.epoch;
          }
          for (;;) {
            size_t i = pool_state.cursor.fetch_add(1);
            if (i >= pool_state.hi) break;
            speculate(i, results[i]);
          }
          if (pool_state.pending.fetch_sub(1) == 1) {
            std::lock_guard<std::mutex> lk(pool_state.m);
            pool_state.cv_done.notify_one();
          }
        }
      });
    }
  }
  auto run_parallel = [&](size_t lo, size_t hi) {
    if (workers.empty() || hi - lo <= 1) {
      for (size_t i = lo; i < hi; i++) speculate(i, results[i]);
      return;
    }
    {
      std::lock_guard<std::mutex> lk(pool_state.m);
      pool_state.lo = lo; pool_state.hi = hi;
      pool_state.cursor.store(lo);
      pool_state.pending.store(workers.size());
      pool_state.epoch++;
    }
    pool_state.cv_work.notify_all();
    std::unique_lock<std::mutex> lk(pool_state.m);
    pool_state.cv_done.wait(lk, [&] { return pool_state.pending.load() == 0; });
  };

  size_t pos = 0;
  for (uint32_t wsize : wave_sizes) {
    size_t lo = pos, hi = pos + wsize;
    pos = hi;
    if (stopped) {
      for (size_t i = lo; i < hi; i++) {
        results[i].index = txs[i].index;
        results[i].status = 3;
      }
      continue;
    }
    // parallel speculation against the wave-start view
    run_parallel(lo, hi);
    // in-order validation + commit (the Python commit loop, natively)
    std::set<Addr> committed_accts;
    std::set<SlotKey> committed_slots;
    for (size_t i = lo; i < hi; i++) {
      if (stopped) { demote(i, 3); continue; }
      if (txs[i].gas_limit > remaining_gas - cumulative) {
        // python raises invalid-block here; hand over
        demote(i, 2); stopped = true; continue;
      }
      bool conflicted = results[i].status == 2 ||
                        results[i].coinbase_sensitive ||
                        intersects_accts(committed_accts, results[i]) ||
                        intersects_slots(committed_slots, results[i]);
      if (conflicted) {
        speculate(i, results[i]);  // serial re-run against the merged view
        exec_mode[i] = 1;
        if (results[i].status == 2 || results[i].coinbase_sensitive) {
          demote(i, 2); stopped = true; continue;
        }
      }
      // commit writes into the view
      for (const auto &kv : results[i].acct_writes) {
        view.acct_overlay[kv.first] = AcctRec{
            kv.second.nonce, kv.second.balance,
            [&]() {  // preserve the code id across balance/nonce writes
              bool known; const AcctRec *prev = view.account(kv.first, known);
              return prev ? prev->code_id : -1;
            }(),
            !kv.second.deleted};
        committed_accts.insert(kv.first);
      }
      for (const auto &kv : results[i].slot_writes) {
        view.slot_overlay[kv.first] = kv.second;
        committed_slots.insert(kv.first);
      }
      cumulative += results[i].gas_used;
    }
  }
  if (!workers.empty()) {
    {
      std::lock_guard<std::mutex> lk(pool_state.m);
      pool_state.quit = true;
    }
    pool_state.cv_work.notify_all();
    for (auto &th : workers) th.join();
  }

  Writer w;
  w.u32((uint32_t)results.size());
  uint8_t be[32];
  for (size_t i = 0; i < results.size(); i++) {
    const TxResult &res = results[i];
    w.u32(res.index);
    w.u8(res.status);
    w.u8(exec_mode[i]);
    w.u8(res.coinbase_sensitive ? 1 : 0);
    w.u64(res.gas_used);
    to_be(res.fee_delta, be); w.append(be, 32);
    w.u32((uint32_t)res.output.size());
    w.append(res.output.data(), res.output.size());
    w.u32((uint32_t)res.logs.size());
    for (const LogRec &lg : res.logs) {
      w.append(lg.a.b, 20);
      w.u8((uint8_t)lg.topics.size());
      for (const auto &t : lg.topics) w.append(t.data(), 32);
      w.u32((uint32_t)lg.data.size());
      w.append(lg.data.data(), lg.data.size());
    }
    w.u32((uint32_t)res.acct_reads.size());
    for (const Addr &a : res.acct_reads) w.append(a.b, 20);
    w.u32((uint32_t)res.acct_writes.size());
    for (const auto &kv : res.acct_writes) {
      w.append(kv.first.b, 20);
      w.u8(kv.second.deleted);
      w.u64(kv.second.nonce);
      to_be(kv.second.balance, be); w.append(be, 32);
    }
    w.u32((uint32_t)res.slot_reads.size());
    for (const SlotKey &k : res.slot_reads) {
      w.append(k.a.b, 20); w.append(k.k, 32);
    }
    w.u32((uint32_t)res.slot_writes.size());
    for (const auto &kv : res.slot_writes) {
      w.append(kv.first.a.b, 20); w.append(kv.first.k, 32);
      to_be(kv.second, be); w.append(be, 32);
    }
  }
  uint8_t *out = (uint8_t *)malloc(w.buf.size());
  memcpy(out, w.buf.data(), w.buf.size());
  *out_len = w.buf.size();
  return out;
}

void evm_free(uint8_t *p) { free(p); }

}  // extern "C"
