// Sanitizer stress driver for the MVCC KV engine (kvstore.cpp).
//
// Reference analogue: the reference relies on MDBX's own battle-tested
// concurrency plus Rust's data-race freedom; this repo's C++ engine gets
// the equivalent assurance from running its reader/writer protocol under
// sanitizers + a logic-level race detector (SURVEY §5: race detection /
// sanitizers).
//
// Build + run (tests/test_native_kv.py::test_sanitized_concurrent_stress):
//   g++ -std=c++17 -O1 -g -fsanitize=address,undefined kvstore.cpp \
//       kvstore_tsan.cpp -o build/kvstore_stress && ./build/kvstore_stress
// (-fsanitize=thread is preferred where libtsan supports the running
// kernel; gcc-12's TSAN runtime SEGVs on 6.18+ kernels, so the test
// harness probes TSAN first and falls back to ASan+UBSan.)
//
// Workload: one writer rewrites ALL keys to value=round and commits,
// while N reader threads open snapshots and iterate. Two failure modes
// are detected: (a) memory errors under the sanitizer, (b) a broken
// snapshot — a reader observing a MIX of rounds inside one iteration
// (exit 2), which is precisely the torn read MVCC must rule out.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* rtkv_open(const char* dir);
void rtkv_close(void* env);
void* rtkv_txn_begin(void* env, int write);
int rtkv_put(void* txn, const char* table, const uint8_t* key, uint32_t klen,
             const uint8_t* val, uint32_t vlen, int dupsort);
int rtkv_get(void* txn, const char* table, const uint8_t* key, uint32_t klen,
             const uint8_t** out, uint32_t* out_len);
uint64_t rtkv_entry_count(void* txn, const char* table);
int rtkv_commit(void* txn);
void rtkv_abort(void* txn);
void* rtkv_cursor(void* txn, const char* table);
int rtkv_cursor_first(void* cur, const uint8_t** k, uint32_t* kl,
                      const uint8_t** v, uint32_t* vl);
int rtkv_cursor_next(void* cur, int skip_dups, const uint8_t** k,
                     uint32_t* kl, const uint8_t** v, uint32_t* vl);
void rtkv_cursor_close(void* cur);
}

static std::atomic<bool> stop{false};
static std::atomic<bool> torn{false};
static std::atomic<long> reads{0};

static void reader(void* env) {
  while (!stop.load(std::memory_order_relaxed)) {
    void* txn = rtkv_txn_begin(env, 0);
    // snapshot iteration: the writer rewrites EVERY key to the same
    // round value per commit, so one snapshot must never mix rounds
    void* cur = rtkv_cursor(txn, "T");
    const uint8_t *k, *v;
    uint32_t kl, vl;
    uint64_t n = 0;
    int seen_round = -1;
    int ok = rtkv_cursor_first(cur, &k, &kl, &v, &vl);
    while (ok) {
      n++;
      if (vl > 0) {
        int r = v[0];
        if (seen_round < 0) seen_round = r;
        else if (r != seen_round) torn.store(true);
      }
      ok = rtkv_cursor_next(cur, 0, &k, &kl, &v, &vl);
    }
    rtkv_cursor_close(cur);
    // a point read against the same snapshot must agree too
    uint8_t key[8] = {0};
    const uint8_t* out;
    uint32_t out_len;
    if (rtkv_get(txn, "T", key, sizeof key, &out, &out_len) && out_len > 0
        && seen_round >= 0 && out[0] != seen_round)
      torn.store(true);
    rtkv_abort(txn);
    reads.fetch_add(static_cast<long>(n), std::memory_order_relaxed);
  }
}

int main() {
  void* env = rtkv_open("");  // in-memory: pure concurrency exercise
  std::vector<std::thread> readers;
  for (int i = 0; i < 4; i++) readers.emplace_back(reader, env);

  for (int round = 0; round < 200; round++) {
    void* txn = rtkv_txn_begin(env, 1);
    for (int i = 0; i < 50; i++) {
      uint8_t key[8], val[16];
      std::memset(key, 0, sizeof key);
      key[0] = static_cast<uint8_t>(i);
      std::memset(val, round & 0xFF, sizeof val);
      rtkv_put(txn, "T", key, sizeof key, val, sizeof val, 0);
    }
    if (rtkv_commit(txn) != 0) {
      std::fprintf(stderr, "commit failed at round %d\n", round);
      return 1;
    }
  }
  stop.store(true);
  for (auto& t : readers) t.join();
  rtkv_close(env);
  if (torn.load()) {
    std::fprintf(stderr, "TORN SNAPSHOT: reader mixed rounds\n");
    return 2;
  }
  std::printf("STRESS_OK reads=%ld\n", reads.load());
  return 0;
}
