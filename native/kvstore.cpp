// reth-tpu native KV storage engine.
//
// Reference analogue: libmdbx (crates/storage/libmdbx-rs/mdbx-sys/libmdbx,
// 37.7k LoC C) — the reference's embedded B+tree store. This engine keeps
// the same contract surface the framework's Database/Tx/Cursor interface
// needs: named tables sorted by key, DUPSORT duplicate lists sorted by
// value, single-writer transactions with MVCC snapshot isolation for
// readers (clone-on-write tables published by one atomic map swap, as
// MDBX does via shadow paging), ordered cursors pinned to their txn view,
// and a write-ahead log + snapshot compaction. Durability scope: commits
// fflush (process-crash-safe; recovery = snapshot + WAL replay of complete
// committed batches); call rtkv_sync for power-loss durability (fsync).
//
// Build: g++ -O2 -std=c++17 -shared -fPIC kvstore.cpp -o libkvstore.so

#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

using Key = std::string;
using Dups = std::vector<std::string>;  // sorted; non-dup tables: size()==1
using Table = std::map<Key, Dups>;

struct Env;

// -- WAL record layout --------------------------------------------------------
// u8 op | u32 table_len | table | u32 key_len | key | u32 val_len | val
// ops: 1=put 2=put_dup 3=del_key 4=del_dup 5=clear_table 6=commit_mark
enum WalOp : uint8_t {
  WAL_PUT = 1,
  WAL_PUT_DUP = 2,
  WAL_DEL_KEY = 3,
  WAL_DEL_DUP = 4,
  WAL_CLEAR = 5,
  WAL_COMMIT = 6,
};

// MVCC: the published table map holds IMMUTABLE tables behind shared_ptr.
// A txn captures the map at begin (its snapshot); a writer clones a table
// on first touch into its private `own` set and publishes all clones with
// one map swap at commit — readers keep their captured pointers for their
// whole lifetime, exactly the reader isolation MDBX gives the reference
// via shadow paging. One writer at a time (writer_mu).
using TableRef = std::shared_ptr<const Table>;

struct Env {
  std::map<std::string, TableRef> tables;
  std::mutex publish_mu;             // guards `tables` capture/swap
  std::mutex writer_mu;              // single writer (+ WAL/snapshot IO)
  std::thread::id writer_owner{};    // nested same-thread writers = error
  std::string dir;       // empty = in-memory only
  FILE* wal = nullptr;
  uint64_t wal_records = 0;

  // open-time only (single-threaded load/replay): mutable access
  Table* open_mutable(const std::string& name) {
    auto it = tables.find(name);
    if (it == tables.end()) {
      auto p = std::make_shared<Table>();
      Table* raw = p.get();
      tables[name] = std::move(p);
      return raw;
    }
    return const_cast<Table*>(it->second.get());
  }

  ~Env() {
    if (wal) fclose(wal);
  }
};

struct Txn {
  Env* env;
  bool write;
  std::map<std::string, TableRef> snap;                 // captured at begin
  std::map<std::string, std::shared_ptr<Table>> own;    // clone-on-write
  // WAL records buffered until commit (atomicity: records + commit mark)
  std::string wal_buf;

  const Table* view(const std::string& t) const {
    auto oi = own.find(t);
    if (oi != own.end()) return oi->second.get();
    auto si = snap.find(t);
    return si != snap.end() ? si->second.get() : nullptr;
  }

  TableRef view_ref(const std::string& t) const {
    auto oi = own.find(t);
    if (oi != own.end()) return oi->second;
    auto si = snap.find(t);
    return si != snap.end() ? si->second : nullptr;
  }

  Table* wview(const std::string& t) {
    auto oi = own.find(t);
    if (oi != own.end()) return oi->second.get();
    auto si = snap.find(t);
    auto p = si != snap.end() ? std::make_shared<Table>(*si->second)
                              : std::make_shared<Table>();
    Table* raw = p.get();
    own[t] = std::move(p);
    return raw;
  }
};

struct Cursor {
  Txn* txn;
  std::string table;
  TableRef pin;          // the table as of cursor creation (kept alive)
  Table::const_iterator it;
  size_t dup = 0;
  // tri-state mirrors the python MemDb cursor: UNPOS (fresh; next()=first),
  // POS (on an entry), EXHAUSTED (failed seek / ran off the end;
  // next()=None but prev()=last — MemDb _ki==len semantics)
  enum State : uint8_t { UNPOS, POS, EXHAUSTED } state = UNPOS;
};

void wal_append(std::string& buf, uint8_t op, const std::string& table,
                const std::string& key, const std::string& val) {
  auto put32 = [&buf](uint32_t v) { buf.append(reinterpret_cast<char*>(&v), 4); };
  buf.push_back(static_cast<char>(op));
  put32(static_cast<uint32_t>(table.size()));
  buf.append(table);
  put32(static_cast<uint32_t>(key.size()));
  buf.append(key);
  put32(static_cast<uint32_t>(val.size()));
  buf.append(val);
}

void table_put(Table& t, const std::string& key, const std::string& val,
               bool dupsort) {
  Dups& d = t[key];
  if (!dupsort) {
    d.assign(1, val);
    return;
  }
  auto pos = std::lower_bound(d.begin(), d.end(), val);
  if (pos == d.end() || *pos != val) d.insert(pos, val);
}

bool table_del(Table& t, const std::string& key, const std::string* val) {
  auto ki = t.find(key);
  if (ki == t.end()) return false;
  if (val == nullptr) {
    t.erase(ki);
    return true;
  }
  Dups& d = ki->second;
  auto pos = std::lower_bound(d.begin(), d.end(), *val);
  if (pos != d.end() && *pos == *val) {
    d.erase(pos);
    if (d.empty()) t.erase(ki);
    return true;
  }
  return false;
}

// -- snapshot format ----------------------------------------------------------
// magic "RTKV1\n" | per table: u32 name_len name u64 nkeys
//   per key: u32 key_len key u32 ndups { u32 len bytes }
// terminated by u32 name_len == 0xFFFFFFFF

bool save_snapshot(Env* env) {
  if (env->dir.empty()) return true;
  std::string tmp = env->dir + "/snapshot.tmp";
  FILE* f = fopen(tmp.c_str(), "wb");
  if (!f) return false;
  bool ok = true;
  auto wr = [f, &ok](const void* p, size_t n) {
    if (n && fwrite(p, 1, n, f) != n) ok = false;
  };
  auto w32 = [&wr](uint32_t v) { wr(&v, 4); };
  auto w64 = [&wr](uint64_t v) { wr(&v, 8); };
  wr("RTKV1\n", 6);
  for (auto& [name, table_ref] : env->tables) {
    const Table& table = *table_ref;
    w32(static_cast<uint32_t>(name.size()));
    wr(name.data(), name.size());
    w64(table.size());
    for (auto& [key, dups] : table) {
      w32(static_cast<uint32_t>(key.size()));
      wr(key.data(), key.size());
      w32(static_cast<uint32_t>(dups.size()));
      for (auto& v : dups) {
        w32(static_cast<uint32_t>(v.size()));
        wr(v.data(), v.size());
      }
    }
  }
  w32(0xFFFFFFFFu);
  if (fflush(f) != 0) ok = false;
  if (ok && fsync(fileno(f)) != 0) ok = false;
  fclose(f);
  if (!ok) {
    remove(tmp.c_str());
    return false;  // keep the old snapshot + WAL intact
  }
  std::string final = env->dir + "/snapshot.rtkv";
  if (rename(tmp.c_str(), final.c_str()) != 0) return false;
  // snapshot now authoritative: truncate the WAL
  if (env->wal) fclose(env->wal);
  std::string walpath = env->dir + "/wal.rtkv";
  env->wal = fopen(walpath.c_str(), "wb");
  env->wal_records = 0;
  return env->wal != nullptr;
}

bool read_exact(FILE* f, void* out, size_t n) { return fread(out, 1, n, f) == n; }

bool load_snapshot(Env* env) {
  std::string path = env->dir + "/snapshot.rtkv";
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) return true;  // fresh env
  char magic[6];
  if (!read_exact(f, magic, 6) || memcmp(magic, "RTKV1\n", 6) != 0) {
    fclose(f);
    return false;
  }
  while (true) {
    uint32_t name_len;
    if (!read_exact(f, &name_len, 4)) break;
    if (name_len == 0xFFFFFFFFu) break;
    std::string name(name_len, '\0');
    if (!read_exact(f, name.data(), name_len)) break;
    uint64_t nkeys;
    if (!read_exact(f, &nkeys, 8)) break;
    Table& t = *env->open_mutable(name);
    for (uint64_t i = 0; i < nkeys; i++) {
      uint32_t klen;
      if (!read_exact(f, &klen, 4)) goto done;
      std::string key(klen, '\0');
      if (!read_exact(f, key.data(), klen)) goto done;
      uint32_t ndups;
      if (!read_exact(f, &ndups, 4)) goto done;
      Dups d;
      d.reserve(ndups);
      for (uint32_t j = 0; j < ndups; j++) {
        uint32_t vlen;
        if (!read_exact(f, &vlen, 4)) goto done;
        std::string v(vlen, '\0');
        if (!read_exact(f, v.data(), vlen)) goto done;
        d.push_back(std::move(v));
      }
      t.emplace(std::move(key), std::move(d));
    }
  }
done:
  fclose(f);
  return true;
}

bool replay_wal(Env* env) {
  std::string path = env->dir + "/wal.rtkv";
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) return true;
  // collect one committed batch at a time; uncommitted tails are dropped
  struct Rec {
    uint8_t op;
    std::string table, key, val;
  };
  std::vector<Rec> batch;
  while (true) {
    uint8_t op;
    if (!read_exact(f, &op, 1)) break;
    uint32_t tlen, klen, vlen;
    std::string table, key, val;
    if (!read_exact(f, &tlen, 4)) break;
    table.resize(tlen);
    if (tlen && !read_exact(f, table.data(), tlen)) break;
    if (!read_exact(f, &klen, 4)) break;
    key.resize(klen);
    if (klen && !read_exact(f, key.data(), klen)) break;
    if (!read_exact(f, &vlen, 4)) break;
    val.resize(vlen);
    if (vlen && !read_exact(f, val.data(), vlen)) break;
    if (op == WAL_COMMIT) {
      for (auto& r : batch) {
        Table& t = *env->open_mutable(r.table);
        switch (r.op) {
          case WAL_PUT: table_put(t, r.key, r.val, false); break;
          case WAL_PUT_DUP: table_put(t, r.key, r.val, true); break;
          case WAL_DEL_KEY: table_del(t, r.key, nullptr); break;
          case WAL_DEL_DUP: table_del(t, r.key, &r.val); break;
          case WAL_CLEAR: t.clear(); break;
        }
      }
      batch.clear();
    } else {
      batch.push_back({op, std::move(table), std::move(key), std::move(val)});
    }
  }
  fclose(f);
  return true;
}

}  // namespace

extern "C" {

void* rtkv_open(const char* dir) {
  auto env = std::make_unique<Env>();
  if (dir && dir[0]) {
    env->dir = dir;
    if (!load_snapshot(env.get())) return nullptr;
    if (!replay_wal(env.get())) return nullptr;
    std::string walpath = env->dir + "/wal.rtkv";
    env->wal = fopen(walpath.c_str(), "ab");
    if (!env->wal) return nullptr;
  }
  return env.release();
}

void rtkv_close(void* envp) { delete static_cast<Env*>(envp); }

int rtkv_snapshot(void* envp) {
  auto env = static_cast<Env*>(envp);
  // exclude writers for the whole snapshot+WAL-truncate window: a racing
  // commit could otherwise mutate the map mid-iteration or write to the
  // WAL handle being swapped out
  std::lock_guard<std::mutex> w(env->writer_mu);
  return save_snapshot(env) ? 0 : -1;
}

// Power-loss durability point: fsync the WAL.
int rtkv_sync(void* envp) {
  auto env = static_cast<Env*>(envp);
  if (!env->wal) return 0;
  if (fflush(env->wal) != 0) return -1;
  return fsync(fileno(env->wal)) == 0 ? 0 : -1;
}

void* rtkv_txn_begin(void* envp, int write) {
  auto env = static_cast<Env*>(envp);
  if (write) {
    // a nested write txn on one thread would deadlock (or, with a
    // recursive lock, silently clobber the outer txn's clones) — error
    if (env->writer_owner == std::this_thread::get_id()) return nullptr;
    env->writer_mu.lock();
    env->writer_owner = std::this_thread::get_id();
  }
  auto txn = new Txn();
  txn->env = env;
  txn->write = write != 0;
  {
    std::lock_guard<std::mutex> g(env->publish_mu);
    txn->snap = env->tables;  // shared_ptr copies: the MVCC snapshot
  }
  return txn;
}

int rtkv_put(void* txnp, const char* table, const uint8_t* key, uint32_t klen,
             const uint8_t* val, uint32_t vlen, int dupsort) {
  auto txn = static_cast<Txn*>(txnp);
  if (!txn->write) return -1;
  std::string t(table), k(reinterpret_cast<const char*>(key), klen),
      v(reinterpret_cast<const char*>(val), vlen);
  table_put(*txn->wview(t), k, v, dupsort != 0);
  wal_append(txn->wal_buf, dupsort ? WAL_PUT_DUP : WAL_PUT, t, k, v);
  return 0;
}

int rtkv_del(void* txnp, const char* table, const uint8_t* key, uint32_t klen,
             const uint8_t* val, uint32_t vlen, int have_val) {
  auto txn = static_cast<Txn*>(txnp);
  if (!txn->write) return -1;
  std::string t(table), k(reinterpret_cast<const char*>(key), klen);
  bool ok;
  if (have_val) {
    std::string v(reinterpret_cast<const char*>(val), vlen);
    ok = table_del(*txn->wview(t), k, &v);
    if (ok) wal_append(txn->wal_buf, WAL_DEL_DUP, t, k, v);
  } else {
    ok = table_del(*txn->wview(t), k, nullptr);
    if (ok) wal_append(txn->wal_buf, WAL_DEL_KEY, t, k, "");
  }
  return ok ? 1 : 0;
}

int rtkv_clear(void* txnp, const char* table) {
  auto txn = static_cast<Txn*>(txnp);
  if (!txn->write) return -1;
  std::string t(table);
  txn->own[t] = std::make_shared<Table>();
  wal_append(txn->wal_buf, WAL_CLEAR, t, "", "");
  return 0;
}

// get: first duplicate; returns 1 found / 0 missing. Pointer valid for the
// life of the txn's snapshot (caller copies immediately anyway).
int rtkv_get(void* txnp, const char* table, const uint8_t* key, uint32_t klen,
             const uint8_t** out, uint32_t* out_len) {
  auto txn = static_cast<Txn*>(txnp);
  const Table* t = txn->view(table);
  if (!t) return 0;
  auto ki = t->find(std::string(reinterpret_cast<const char*>(key), klen));
  if (ki == t->end() || ki->second.empty()) return 0;
  *out = reinterpret_cast<const uint8_t*>(ki->second[0].data());
  *out_len = static_cast<uint32_t>(ki->second[0].size());
  return 1;
}

uint64_t rtkv_entry_count(void* txnp, const char* table) {
  auto txn = static_cast<Txn*>(txnp);
  const Table* t = txn->view(table);
  if (!t) return 0;
  uint64_t n = 0;
  for (auto& [k, d] : *t) n += d.size();
  return n;
}

int rtkv_commit(void* txnp) {
  auto txn = static_cast<Txn*>(txnp);
  int rc = 0;
  if (txn->write) {
    if (txn->env->wal && !txn->wal_buf.empty()) {
      wal_append(txn->wal_buf, WAL_COMMIT, "", "", "");
      if (fwrite(txn->wal_buf.data(), 1, txn->wal_buf.size(), txn->env->wal) !=
          txn->wal_buf.size())
        rc = -1;
      fflush(txn->env->wal);
      txn->env->wal_records += 1;
    }
    if (!txn->own.empty()) {
      std::lock_guard<std::mutex> g(txn->env->publish_mu);
      for (auto& [name, tbl] : txn->own) txn->env->tables[name] = tbl;
    }
    txn->env->writer_owner = std::thread::id{};
    txn->env->writer_mu.unlock();
  }
  delete txn;
  return rc;
}

void rtkv_abort(void* txnp) {
  auto txn = static_cast<Txn*>(txnp);
  if (txn->write) {  // clones just drop
    txn->env->writer_owner = std::thread::id{};
    txn->env->writer_mu.unlock();
  }
  delete txn;
}

// -- cursors ------------------------------------------------------------------

void* rtkv_cursor(void* txnp, const char* table) {
  auto txn = static_cast<Txn*>(txnp);
  auto cur = new Cursor();
  cur->txn = txn;
  cur->table = table;
  cur->pin = txn->view_ref(table);  // tx view as of cursor creation
  cur->state = Cursor::UNPOS;
  return cur;
}

void rtkv_cursor_close(void* curp) { delete static_cast<Cursor*>(curp); }

namespace {

const Table* cursor_table(Cursor* c) { return c->pin.get(); }

// MemDb cursor semantics: the KEY order is frozen at cursor creation (the
// pin), but VALUES are read through the txn's live view — a write txn's
// own later puts/deletes are visible to pre-existing cursors.
const Dups* live_dups(Cursor* c, const Key& key) {
  const Table* t = c->txn->view(c->table);
  if (!t) return nullptr;
  auto ki = t->find(key);
  return ki == t->end() ? nullptr : &ki->second;
}

int emit(Cursor* c, const uint8_t** k, uint32_t* klen, const uint8_t** v,
         uint32_t* vlen) {
  if (c->state != Cursor::POS) return 0;
  const Key& key = c->it->first;
  const Dups* d = live_dups(c, key);
  if (!d || c->dup >= d->size()) return 0;
  *k = reinterpret_cast<const uint8_t*>(key.data());
  *klen = static_cast<uint32_t>(key.size());
  *v = reinterpret_cast<const uint8_t*>((*d)[c->dup].data());
  *vlen = static_cast<uint32_t>((*d)[c->dup].size());
  return 1;
}

}  // namespace

int rtkv_cursor_first(void* curp, const uint8_t** k, uint32_t* klen,
                      const uint8_t** v, uint32_t* vlen) {
  auto c = static_cast<Cursor*>(curp);
  const Table* t = cursor_table(c);
  if (!t || t->empty()) {
    c->state = Cursor::EXHAUSTED;
    return 0;
  }
  c->it = t->begin();
  c->dup = 0;
  c->state = Cursor::POS;
  return emit(c, k, klen, v, vlen);
}

int rtkv_cursor_last(void* curp, const uint8_t** k, uint32_t* klen,
                     const uint8_t** v, uint32_t* vlen) {
  auto c = static_cast<Cursor*>(curp);
  const Table* t = cursor_table(c);
  if (!t || t->empty()) {
    c->state = Cursor::EXHAUSTED;
    return 0;
  }
  c->it = std::prev(t->end());
  const Dups* d = live_dups(c, c->it->first);
  c->dup = (d && d->size()) ? d->size() - 1 : 0;
  c->state = Cursor::POS;
  return emit(c, k, klen, v, vlen);
}

int rtkv_cursor_seek(void* curp, const uint8_t* key, uint32_t klen, int exact,
                     const uint8_t** k, uint32_t* kl, const uint8_t** v,
                     uint32_t* vl) {
  auto c = static_cast<Cursor*>(curp);
  const Table* t = cursor_table(c);
  c->state = Cursor::EXHAUSTED;
  if (!t) return 0;
  std::string target(reinterpret_cast<const char*>(key), klen);
  auto it = t->lower_bound(target);
  if (it == t->end()) return 0;
  if (exact && it->first != target) return 0;
  c->it = it;
  c->dup = 0;
  c->state = Cursor::POS;
  return emit(c, k, kl, v, vl);
}

int rtkv_cursor_next(void* curp, int skip_dups, const uint8_t** k, uint32_t* kl,
                     const uint8_t** v, uint32_t* vl) {
  auto c = static_cast<Cursor*>(curp);
  const Table* t = cursor_table(c);
  if (!t) {
    c->state = Cursor::EXHAUSTED;
    return 0;
  }
  if (c->state == Cursor::EXHAUSTED) return 0;  // MemDb: past-the-end stays put
  if (c->state == Cursor::UNPOS) return rtkv_cursor_first(curp, k, kl, v, vl);
  const Dups* cd = live_dups(c, c->it->first);
  if (!skip_dups && cd && c->dup + 1 < cd->size()) {
    c->dup += 1;
    return emit(c, k, kl, v, vl);
  }
  ++c->it;
  c->dup = 0;
  if (c->it == t->end()) {
    c->state = Cursor::EXHAUSTED;
    return 0;
  }
  return emit(c, k, kl, v, vl);
}

int rtkv_cursor_prev(void* curp, const uint8_t** k, uint32_t* kl,
                     const uint8_t** v, uint32_t* vl) {
  auto c = static_cast<Cursor*>(curp);
  const Table* t = cursor_table(c);
  if (!t || c->state == Cursor::UNPOS) return 0;
  if (c->state == Cursor::EXHAUSTED)  // MemDb: prev from past-the-end = last
    return rtkv_cursor_last(curp, k, kl, v, vl);
  if (c->dup > 0) {
    c->dup -= 1;
    return emit(c, k, kl, v, vl);
  }
  if (c->it == t->begin()) {
    c->state = Cursor::UNPOS;
    return 0;
  }
  --c->it;
  const Dups* pd = live_dups(c, c->it->first);
  c->dup = (pd && pd->size()) ? pd->size() - 1 : 0;
  return emit(c, k, kl, v, vl);
}

// next duplicate of the CURRENT key only; 0 when exhausted
int rtkv_cursor_next_dup(void* curp, const uint8_t** k, uint32_t* kl,
                         const uint8_t** v, uint32_t* vl) {
  auto c = static_cast<Cursor*>(curp);
  if (c->state != Cursor::POS) return 0;
  const Dups* d = live_dups(c, c->it->first);
  if (!d || c->dup + 1 >= d->size()) return 0;
  c->dup += 1;
  return emit(c, k, kl, v, vl);
}

// first duplicate of `key` with value >= subkey prefix
int rtkv_cursor_seek_dup(void* curp, const uint8_t* key, uint32_t klen,
                         const uint8_t* sub, uint32_t slen, const uint8_t** k,
                         uint32_t* kl, const uint8_t** v, uint32_t* vl) {
  auto c = static_cast<Cursor*>(curp);
  const Table* t = cursor_table(c);
  c->state = Cursor::EXHAUSTED;
  if (!t) return 0;
  auto it = t->find(std::string(reinterpret_cast<const char*>(key), klen));
  if (it == t->end()) return 0;
  std::string target(reinterpret_cast<const char*>(sub), slen);
  c->it = it;
  const Dups* d = live_dups(c, it->first);
  if (!d) return 0;
  auto pos = std::lower_bound(d->begin(), d->end(), target);
  if (pos == d->end()) return 0;
  c->dup = static_cast<size_t>(pos - d->begin());
  c->state = Cursor::POS;
  return emit(c, k, kl, v, vl);
}

}  // extern "C"
