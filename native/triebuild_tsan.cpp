// Sanitizer stress driver for the trie-structure builder (triebuild.cpp).
//
// The rebuild pipeline (reth_tpu/trie/turbo.py RebuildPipeline) calls
// rtb_build from a THREAD POOL — concurrent sweeps over shared read-only
// key/value arrays, each producing its own handle. triebuild.cpp holds no
// global state, and this driver proves it the same way kvstore_tsan.cpp
// proves the MVCC engine: run the real access pattern under TSAN (ASan+
// UBSan fallback where gcc's libtsan breaks on the running kernel).
//
// Build + run (tests/test_turbo_pipeline.py::test_triebuild_threaded_stress):
//   g++ -std=c++17 -O1 -g -fsanitize=thread triebuild.cpp \
//       triebuild_tsan.cpp -o build/triebuild_stress && ./build/triebuild_stress
//
// Workload: N threads × R rounds. Odd threads sweep a PRIVATE key set;
// even threads all sweep the SAME shared arrays concurrently (the
// pipeline's job-list sharing). Two failure modes: (a) memory/race errors
// under the sanitizer, (b) nondeterminism — any round whose level count,
// max slot, or packed byte total differs from round 0 (exit 2).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <algorithm>
#include <thread>
#include <vector>

extern "C" {
void* rtb_build(const uint8_t* keys, uint64_t n_keys, const uint64_t* job_off,
                uint32_t n_jobs, const uint8_t* values, const uint64_t* val_off,
                int collect_meta, int start_depth, int* err);
void rtb_free(void* h);
int32_t rtb_num_levels(void* h);
int32_t rtb_max_slot(void* h);
uint64_t rtb_packed_bytes(void* h, int32_t i);
uint64_t rtb_meta_count(void* h);
}

static std::atomic<bool> failed{false};
static std::atomic<long> builds{0};

struct Input {
    std::vector<uint8_t> keys;     // n x 32, sorted unique
    std::vector<uint64_t> job_off; // [0, n]
    std::vector<uint8_t> values;   // 1 byte per key
    std::vector<uint64_t> val_off;
};

static Input make_input(uint64_t seed, int n) {
    // LCG-filled 32-byte keys, sorted + deduped (rtb_build requires both)
    std::vector<std::vector<uint8_t>> raw(n, std::vector<uint8_t>(32));
    uint64_t s = seed * 6364136223846793005ULL + 1442695040888963407ULL;
    for (auto& k : raw)
        for (int b = 0; b < 32; b++) {
            s = s * 6364136223846793005ULL + 1442695040888963407ULL;
            k[b] = uint8_t(s >> 33);
        }
    std::sort(raw.begin(), raw.end());
    raw.erase(std::unique(raw.begin(), raw.end()), raw.end());
    Input in;
    for (auto& k : raw) in.keys.insert(in.keys.end(), k.begin(), k.end());
    uint64_t cnt = raw.size();
    in.job_off = {0, cnt};
    in.values.resize(cnt, 0x41);  // single byte < 0x80 self-encodes
    in.val_off.resize(cnt + 1);
    for (uint64_t i = 0; i <= cnt; i++) in.val_off[i] = i;
    return in;
}

static void worker(const Input* in, int rounds, int collect) {
    int64_t want_levels = -1, want_slot = -1;
    uint64_t want_bytes = 0;
    for (int r = 0; r < rounds && !failed.load(); r++) {
        int err = 0;
        void* h = rtb_build(in->keys.data(), in->job_off[1], in->job_off.data(),
                            1, in->values.data(), in->val_off.data(),
                            collect, 0, &err);
        if (!h || err) {
            std::fprintf(stderr, "build failed err=%d\n", err);
            failed.store(true);
            return;
        }
        int32_t levels = rtb_num_levels(h);
        int32_t slot = rtb_max_slot(h);
        uint64_t bytes = 0;
        for (int32_t i = 0; i < levels; i++) bytes += rtb_packed_bytes(h, i);
        if (collect) bytes += rtb_meta_count(h);
        rtb_free(h);
        if (r == 0) {
            want_levels = levels; want_slot = slot; want_bytes = bytes;
        } else if (levels != want_levels || slot != want_slot ||
                   bytes != want_bytes) {
            std::fprintf(stderr, "NONDETERMINISM: round %d differs\n", r);
            failed.store(true);
            return;
        }
        builds.fetch_add(1, std::memory_order_relaxed);
    }
}

int main() {
    const int kThreads = 6, kRounds = 24, kKeys = 1200;
    Input shared = make_input(7, kKeys);
    std::vector<Input> privates;
    for (int t = 0; t < kThreads; t += 2)
        privates.push_back(make_input(100 + t, kKeys / 2));
    std::vector<std::thread> ts;
    size_t p = 0;
    for (int t = 0; t < kThreads; t++) {
        const Input* in = (t % 2 == 0) ? &shared : &privates[p++ % privates.size()];
        ts.emplace_back(worker, in, kRounds, t % 2);
    }
    for (auto& t : ts) t.join();
    if (failed.load()) return 2;
    std::printf("STRESS_OK builds=%ld\n", builds.load());
    return 0;
}
