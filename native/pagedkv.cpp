// Paged copy-on-write B+tree KV engine with mmap reads — the MDBX analogue.
//
// Reference analogue: crates/storage/libmdbx-rs/mdbx-sys/libmdbx (shadow-paging
// B+tree). This is NOT a translation of libmdbx: it is a from-scratch C++17
// engine with the same architectural properties the reference relies on:
//
//   * single data file of 4 KiB pages, read through one large shared mmap —
//     the OS page cache IS the read cache, nothing is held in process RAM
//     (unlike native/kvstore.cpp whose std::map holds the whole DB);
//   * copy-on-write page updates: a writer never touches a page any reader
//     (or the last durable version) can see — MVCC snapshot isolation falls
//     out of the design, readers are zero-cost and never block;
//   * dual meta pages flipped on commit: pwrite dirty pages -> fdatasync ->
//     write meta slot (txnid & 1) -> fdatasync. A crash at any point leaves
//     the previous meta valid — no WAL, no replay, O(1) recovery;
//   * freed pages are recycled through a persisted free list once no live
//     reader snapshot can reference them (reader table in memory — single
//     process — so crash recovery can reuse everything in the list);
//   * DUPSORT: per-key sorted duplicate sets, inline in the leaf cell while
//     small, spilled to a nested B+tree when large (sub-database, as MDBX);
//   * overflow page chains for values larger than a leaf cell.
//
// Deliberate simplifications vs libmdbx (documented, not hidden): pages are
// not rebalanced on underflow (only emptied pages are unlinked; heavy delete
// workloads reclaim space through the free list, not by merging siblings),
// and the reader table is in-memory because the embedding is single-process.
//
// C ABI mirrors native/kvstore.cpp (rtpg_ prefix) so the ctypes binding and
// every storage contract test run unchanged over both engines.

#include <algorithm>
#include <cassert>
#include <cstddef>
#include <cstdint>
#include <cstring>
#include <string_view>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t PAGE = 4096;
constexpr uint32_t MAGIC = 0x52545047;  // "RTPG"
constexpr uint32_t VERSION = 1;
constexpr uint64_t MAPSIZE = 1ULL << 40;  // 1 TiB of reserved address space
constexpr uint32_t MAXKEY = 1024;
constexpr uint32_t MAXCELL = 1000;   // largest in-leaf cell => >=4 cells/page
constexpr uint32_t DUP_SPILL = 512;  // inline dup payload before subtree spill

enum PType : uint8_t { P_BRANCH = 1, P_LEAF = 2, P_OVERFLOW = 3, P_FREE = 4 };
enum LFlag : uint8_t { L_INLINE = 0, L_OVERFLOW = 1, L_DUPIN = 2, L_DUPTREE = 3 };

#pragma pack(push, 1)
struct Meta {
  uint32_t magic;
  uint32_t version;
  uint64_t txnid;
  uint64_t n_pages;
  uint32_t catalog_root;
  uint32_t freelist_head;
  uint64_t freelist_len;
  uint64_t checksum;
};
struct PageHdr {
  uint8_t type;
  uint8_t pad;
  uint16_t n_cells;
  uint16_t cells_start;  // lowest cell byte offset (== PAGE when empty)
  uint16_t pad2;
};
#pragma pack(pop)

uint64_t fnv(const void* data, size_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(data);
  uint64_t h = 1469598103934665603ULL;
  for (size_t i = 0; i < n; i++) h = (h ^ p[i]) * 1099511628211ULL;
  return h;
}

uint64_t meta_sum(const Meta& m) { return fnv(&m, offsetof(Meta, checksum)); }

// -- little-endian field access ----------------------------------------------

uint16_t g16(const uint8_t* p) { uint16_t v; memcpy(&v, p, 2); return v; }
uint32_t g32(const uint8_t* p) { uint32_t v; memcpy(&v, p, 4); return v; }
uint64_t g64(const uint8_t* p) { uint64_t v; memcpy(&v, p, 8); return v; }
void s16(uint8_t* p, uint16_t v) { memcpy(p, &v, 2); }
void s32(uint8_t* p, uint32_t v) { memcpy(p, &v, 4); }
void s64(uint8_t* p, uint64_t v) { memcpy(p, &v, 8); }

// -- cells --------------------------------------------------------------------
// Leaf cell:   [u8 flags][u8 pad][u16 klen][u32 vlen][key][payload]
//   L_INLINE:  payload = value bytes (payload size == vlen)
//   L_OVERFLOW:payload = u32 first overflow pgno (vlen = total value length)
//   L_DUPIN:   payload = u32 count, then per dup {u16 len, bytes}
//              (vlen = payload size)
//   L_DUPTREE: payload = u32 subtree root, u64 dup count (vlen = 12)
// Branch cell: [u16 klen][u32 child][key]   (cell 0's key is ignored: -inf)

struct LeafView {
  uint8_t flags;
  std::string_view key;
  uint32_t vlen;
  const uint8_t* payload;
  uint32_t payload_sz;
};

LeafView leaf_view(const uint8_t* c) {
  LeafView v;
  v.flags = c[0];
  uint16_t klen = g16(c + 2);
  v.vlen = g32(c + 4);
  v.key = std::string_view(reinterpret_cast<const char*>(c + 8), klen);
  v.payload = c + 8 + klen;
  v.payload_sz = (v.flags == L_INLINE)     ? v.vlen
                 : (v.flags == L_OVERFLOW) ? 4
                 : (v.flags == L_DUPIN)    ? v.vlen
                                           : 12;
  return v;
}

std::string make_leaf_cell(uint8_t flags, std::string_view key, uint32_t vlen,
                           const void* payload, uint32_t psz) {
  std::string c(8 + key.size() + psz, '\0');
  uint8_t* p = reinterpret_cast<uint8_t*>(c.data());
  p[0] = flags;
  s16(p + 2, static_cast<uint16_t>(key.size()));
  s32(p + 4, vlen);
  memcpy(p + 8, key.data(), key.size());
  if (psz) memcpy(p + 8 + key.size(), payload, psz);
  return c;
}

std::string_view branch_key(const uint8_t* c) {
  return std::string_view(reinterpret_cast<const char*>(c + 6), g16(c));
}
uint32_t branch_child(const uint8_t* c) { return g32(c + 2); }

std::string make_branch_cell(std::string_view key, uint32_t child) {
  std::string c(6 + key.size(), '\0');
  uint8_t* p = reinterpret_cast<uint8_t*>(c.data());
  s16(p, static_cast<uint16_t>(key.size()));
  s32(p + 2, child);
  memcpy(p + 6, key.data(), key.size());
  return c;
}

// -- page layout --------------------------------------------------------------

const PageHdr* hdr(const uint8_t* p) { return reinterpret_cast<const PageHdr*>(p); }
PageHdr* hdr(uint8_t* p) { return reinterpret_cast<PageHdr*>(p); }
const uint8_t* cell_at(const uint8_t* p, int i) {
  return p + g16(p + sizeof(PageHdr) + 2 * i);
}

std::vector<std::string> explode(const uint8_t* p) {
  int n = hdr(p)->n_cells;
  std::vector<std::string> cells;
  cells.reserve(n);
  bool leaf = hdr(p)->type == P_LEAF;
  for (int i = 0; i < n; i++) {
    const uint8_t* c = cell_at(p, i);
    size_t sz;
    if (leaf) {
      LeafView v = leaf_view(c);
      sz = 8 + v.key.size() + v.payload_sz;
    } else {
      sz = 6 + g16(c);
    }
    cells.emplace_back(reinterpret_cast<const char*>(c), sz);
  }
  return cells;
}

size_t cells_bytes(const std::vector<std::string>& cells, size_t a, size_t b) {
  size_t total = 0;
  for (size_t i = a; i < b; i++) total += cells[i].size() + 2;
  return total;
}

bool fits(const std::vector<std::string>& cells) {
  return sizeof(PageHdr) + cells_bytes(cells, 0, cells.size()) <= PAGE;
}

void rebuild(uint8_t* p, uint8_t type, const std::vector<std::string>& cells,
             size_t a, size_t b) {
  memset(p, 0, PAGE);
  PageHdr* h = hdr(p);
  h->type = type;
  h->n_cells = static_cast<uint16_t>(b - a);
  uint32_t off = PAGE;
  for (size_t i = a; i < b; i++) {
    off -= static_cast<uint32_t>(cells[i].size());
    memcpy(p + off, cells[i].data(), cells[i].size());
    s16(p + sizeof(PageHdr) + 2 * (i - a), static_cast<uint16_t>(off));
  }
  h->cells_start = static_cast<uint16_t>(off);
}

// -- env / txn ----------------------------------------------------------------

struct TableInfo {
  uint32_t root = 0;
  uint64_t count = 0;
  bool dirty = false;
};

struct Env {
  int fd = -1;
  std::string dir;
  uint8_t* map = nullptr;
  ~Env() {
    if (map && map != MAP_FAILED) munmap(map, MAPSIZE);
    if (fd >= 0) ::close(fd);
  }
  Meta meta{};
  std::mutex writer_mu;  // serializes write txns
  std::thread::id writer_owner{};
  std::mutex state_mu;  // readers / free lists / meta swap
  std::multiset<uint64_t> readers;
  std::vector<uint32_t> reusable;
  std::vector<std::pair<uint64_t, std::vector<uint32_t>>> pending;
  std::vector<uint32_t> freelist_pages;  // current persisted chain
};

struct Txn {
  Env* env;
  bool write;
  // One txn may be shared by several Python threads (the engine's prewarm
  // workers all read through one provider txn); ctypes releases the GIL, so
  // every entry point serializes on this. Same rule as MDBX: a txn is not
  // concurrently usable — we enforce it with a lock instead of UB.
  // Recursive: cursor_next re-enters via cursor_first (UNPOS semantics).
  std::recursive_mutex op_mu;
  Meta snap;
  std::unordered_map<uint32_t, std::unique_ptr<uint8_t[]>> dirty;
  std::unordered_set<uint32_t> fresh;  // allocated this txn (never durable)
  std::vector<uint32_t> freed;         // prev-version pages freed this txn
  std::vector<uint32_t> recycle;       // fresh pages freed again (reuse now)
  std::vector<uint32_t> took_reusable;  // popped from env->reusable (abort undo)
  uint64_t next_page;
  std::map<std::string, TableInfo> tables;
  std::string valbuf;
};

const uint8_t* tx_page(Txn* t, uint32_t pgno) {
  auto it = t->dirty.find(pgno);
  if (it != t->dirty.end()) return it->second.get();
  return t->env->map + static_cast<uint64_t>(pgno) * PAGE;
}

uint8_t* tx_writable(Txn* t, uint32_t pgno) {
  auto it = t->dirty.find(pgno);
  assert(it != t->dirty.end());
  return it->second.get();
}

void drain_pending(Env* env) {  // caller holds state_mu
  uint64_t min_reader =
      env->readers.empty() ? UINT64_MAX : *env->readers.begin();
  auto& pend = env->pending;
  for (auto it = pend.begin(); it != pend.end();) {
    if (it->first <= env->meta.txnid && it->first <= min_reader) {
      env->reusable.insert(env->reusable.end(), it->second.begin(),
                           it->second.end());
      it = pend.erase(it);
    } else {
      ++it;
    }
  }
}

uint32_t tx_alloc(Txn* t) {
  uint32_t pgno;
  if (!t->recycle.empty()) {
    pgno = t->recycle.back();
    t->recycle.pop_back();
  } else {
    std::lock_guard<std::mutex> g(t->env->state_mu);
    drain_pending(t->env);
    if (!t->env->reusable.empty()) {
      pgno = t->env->reusable.back();
      t->env->reusable.pop_back();
      t->took_reusable.push_back(pgno);
    } else {
      pgno = static_cast<uint32_t>(t->next_page++);
    }
  }
  auto buf = std::make_unique<uint8_t[]>(PAGE);
  memset(buf.get(), 0, PAGE);
  hdr(buf.get())->cells_start = static_cast<uint16_t>(PAGE & 0xFFFF);
  t->dirty[pgno] = std::move(buf);
  t->fresh.insert(pgno);
  return pgno;
}

void tx_free(Txn* t, uint32_t pgno) {
  if (t->fresh.count(pgno)) {
    t->fresh.erase(pgno);
    t->dirty.erase(pgno);
    t->recycle.push_back(pgno);
  } else {
    t->freed.push_back(pgno);
  }
}

// copy-on-write: returns a dirty pgno holding this page's bytes
uint32_t tx_cow(Txn* t, uint32_t pgno) {
  if (t->dirty.count(pgno)) return pgno;
  uint32_t np = tx_alloc(t);
  memcpy(tx_writable(t, np), t->env->map + static_cast<uint64_t>(pgno) * PAGE,
         PAGE);
  tx_free(t, pgno);
  return np;
}

// -- overflow chains ----------------------------------------------------------

constexpr uint32_t OV_DATA = PAGE - 8;  // [u8 type][u8 pad][u16 used][u32 next]

uint32_t ov_write(Txn* t, const uint8_t* data, uint32_t len) {
  uint32_t first = 0, prev = 0;
  uint32_t off = 0;
  while (off < len || first == 0) {
    uint32_t pg = tx_alloc(t);
    uint8_t* p = tx_writable(t, pg);
    p[0] = P_OVERFLOW;
    uint32_t chunk = std::min(OV_DATA, len - off);
    s16(p + 2, static_cast<uint16_t>(chunk));
    s32(p + 4, 0);
    memcpy(p + 8, data + off, chunk);
    off += chunk;
    if (!first)
      first = pg;
    else
      s32(tx_writable(t, prev) + 4, pg);
    prev = pg;
    if (off >= len) break;
  }
  return first;
}

void ov_read(Txn* t, uint32_t pgno, std::string& out) {
  out.clear();
  while (pgno) {
    const uint8_t* p = tx_page(t, pgno);
    out.append(reinterpret_cast<const char*>(p + 8), g16(p + 2));
    pgno = g32(p + 4);
  }
}

void ov_free(Txn* t, uint32_t pgno) {
  while (pgno) {
    uint32_t next = g32(tx_page(t, pgno) + 4);
    tx_free(t, pgno);
    pgno = next;
  }
}

// -- tree search --------------------------------------------------------------

struct PathEnt {
  uint32_t pgno;
  int idx;
};
using Path = std::vector<PathEnt>;

int branch_find(const uint8_t* p, std::string_view key) {
  int n = hdr(p)->n_cells;
  int lo = 1, hi = n;  // cell 0's key is -inf
  while (lo < hi) {
    int mid = (lo + hi) / 2;
    if (branch_key(cell_at(p, mid)) <= key)
      lo = mid + 1;
    else
      hi = mid;
  }
  return lo - 1;
}

int leaf_lower_bound(const uint8_t* p, std::string_view key, bool* exact) {
  int n = hdr(p)->n_cells;
  int lo = 0, hi = n;
  while (lo < hi) {
    int mid = (lo + hi) / 2;
    if (leaf_view(cell_at(p, mid)).key < key)
      lo = mid + 1;
    else
      hi = mid;
  }
  *exact = lo < n && leaf_view(cell_at(p, lo)).key == key;
  return lo;
}

// Descends to the leaf containing (or insertion point of) key. When
// for_write, every page on the path is COWed and parent child pointers are
// patched, so the caller can mutate path pages freely.
bool tree_descend(Txn* t, uint32_t* root, std::string_view key, Path& path,
                  bool for_write, bool* exact) {
  path.clear();
  *exact = false;
  if (!*root) return false;
  uint32_t pg = *root;
  if (for_write) {
    pg = tx_cow(t, pg);
    *root = pg;
  }
  while (true) {
    const uint8_t* p = tx_page(t, pg);
    if (hdr(p)->type == P_BRANCH) {
      int idx = branch_find(p, key);
      uint32_t child = branch_child(cell_at(p, idx));
      if (for_write) {
        uint32_t nc = tx_cow(t, child);
        if (nc != child) {
          uint8_t* wp = tx_writable(t, pg);
          s32(wp + g16(wp + sizeof(PageHdr) + 2 * idx) + 2, nc);
          child = nc;
        }
      }
      path.push_back({pg, idx});
      pg = child;
    } else {
      int idx = leaf_lower_bound(p, key, exact);
      path.push_back({pg, idx});
      return *exact;
    }
  }
}

void descend_edge(Txn* t, uint32_t root, bool last, Path& path) {
  path.clear();
  if (!root) return;
  uint32_t pg = root;
  while (true) {
    const uint8_t* p = tx_page(t, pg);
    int n = hdr(p)->n_cells;
    if (hdr(p)->type == P_BRANCH) {
      int idx = last ? n - 1 : 0;
      path.push_back({pg, idx});
      pg = branch_child(cell_at(p, idx));
    } else {
      path.push_back({pg, last ? n - 1 : 0});
      return;
    }
  }
}

// step the path to the next/prev leaf cell; false when off the end
bool path_step(Txn* t, Path& path, int dir) {
  if (path.empty()) return false;
  int leaf_level = static_cast<int>(path.size()) - 1;
  path[leaf_level].idx += dir;
  const uint8_t* leaf = tx_page(t, path[leaf_level].pgno);
  if (path[leaf_level].idx >= 0 &&
      path[leaf_level].idx < hdr(leaf)->n_cells)
    return true;
  // climb
  int lvl = leaf_level - 1;
  while (lvl >= 0) {
    const uint8_t* p = tx_page(t, path[lvl].pgno);
    int ni = path[lvl].idx + dir;
    if (ni >= 0 && ni < hdr(p)->n_cells) {
      path[lvl].idx = ni;
      // descend along the opposite edge
      uint32_t pg = branch_child(cell_at(p, ni));
      path.resize(lvl + 1);
      while (true) {
        const uint8_t* q = tx_page(t, pg);
        int n = hdr(q)->n_cells;
        int idx = dir > 0 ? 0 : n - 1;
        path.push_back({pg, idx});
        if (hdr(q)->type == P_LEAF) return true;
        pg = branch_child(cell_at(q, idx));
      }
    }
    lvl--;
  }
  return false;
}

// -- tree mutation ------------------------------------------------------------

void branch_insert(Txn* t, uint32_t* root, Path& path, int level,
                   std::string sep, uint32_t right);

// Replace (replace=true) or insert the cell at path's leaf position,
// splitting up the tree as needed. Path pages must already be COWed.
void leaf_put_cell(Txn* t, uint32_t* root, Path& path, std::string cell,
                   bool replace) {
  PathEnt& leaf = path.back();
  uint8_t* p = tx_writable(t, leaf.pgno);
  auto cells = explode(p);
  if (replace)
    cells[leaf.idx] = std::move(cell);
  else
    cells.insert(cells.begin() + leaf.idx, std::move(cell));
  if (fits(cells)) {
    rebuild(p, P_LEAF, cells, 0, cells.size());
    return;
  }
  // split at the byte midpoint
  size_t total = cells_bytes(cells, 0, cells.size());
  size_t acc = 0, cut = 1;
  for (size_t i = 0; i < cells.size() - 1; i++) {
    acc += cells[i].size() + 2;
    if (acc >= total / 2) {
      cut = i + 1;
      break;
    }
  }
  uint32_t rpg = tx_alloc(t);
  rebuild(tx_writable(t, rpg), P_LEAF, cells, cut, cells.size());
  rebuild(p, P_LEAF, cells, 0, cut);
  std::string sep(leaf_view(cell_at(tx_page(t, rpg), 0)).key);
  branch_insert(t, root, path, static_cast<int>(path.size()) - 2,
                std::move(sep), rpg);
}

void branch_insert(Txn* t, uint32_t* root, Path& path, int level,
                   std::string sep, uint32_t right) {
  if (level < 0) {  // the root itself split: grow the tree by one level
    uint32_t npg = tx_alloc(t);
    std::vector<std::string> cells;
    cells.push_back(make_branch_cell("", path[0].pgno));
    cells.push_back(make_branch_cell(sep, right));
    rebuild(tx_writable(t, npg), P_BRANCH, cells, 0, cells.size());
    *root = npg;
    return;
  }
  PathEnt& ent = path[level];
  uint8_t* p = tx_writable(t, ent.pgno);
  auto cells = explode(p);
  cells.insert(cells.begin() + ent.idx + 1, make_branch_cell(sep, right));
  if (fits(cells)) {
    rebuild(p, P_BRANCH, cells, 0, cells.size());
    return;
  }
  size_t total = cells_bytes(cells, 0, cells.size());
  size_t acc = 0, cut = 1;
  for (size_t i = 0; i < cells.size() - 1; i++) {
    acc += cells[i].size() + 2;
    if (acc >= total / 2) {
      cut = i + 1;
      break;
    }
  }
  uint32_t rpg = tx_alloc(t);
  rebuild(tx_writable(t, rpg), P_BRANCH, cells, cut, cells.size());
  rebuild(p, P_BRANCH, cells, 0, cut);
  std::string up(branch_key(cell_at(tx_page(t, rpg), 0)));
  branch_insert(t, root, path, level - 1, std::move(up), rpg);
}

void tree_remove_at(Txn* t, uint32_t* root, Path& path) {
  int level = static_cast<int>(path.size()) - 1;
  while (level >= 0) {
    PathEnt& ent = path[level];
    uint8_t* p = tx_writable(t, ent.pgno);
    auto cells = explode(p);
    cells.erase(cells.begin() + ent.idx);
    if (!cells.empty()) {
      rebuild(p, hdr(p)->type, cells, 0, cells.size());
      break;
    }
    tx_free(t, ent.pgno);
    if (level == 0) {
      *root = 0;
      return;
    }
    level--;
  }
  // collapse a single-child root chain
  while (*root) {
    const uint8_t* p = tx_page(t, *root);
    if (hdr(p)->type != P_BRANCH || hdr(p)->n_cells != 1) break;
    uint32_t child = branch_child(cell_at(p, 0));
    tx_free(t, *root);
    *root = child;
  }
}

// -- dup payload helpers ------------------------------------------------------

std::vector<std::string> dup_unpack(const uint8_t* payload, uint32_t psz) {
  std::vector<std::string> out;
  uint32_t count = g32(payload);
  const uint8_t* p = payload + 4;
  const uint8_t* end = payload + psz;
  for (uint32_t i = 0; i < count && p + 2 <= end; i++) {
    uint16_t len = g16(p);
    p += 2;
    out.emplace_back(reinterpret_cast<const char*>(p), len);
    p += len;
  }
  return out;
}

std::string dup_pack(const std::vector<std::string>& dups) {
  std::string out(4, '\0');
  s32(reinterpret_cast<uint8_t*>(out.data()),
      static_cast<uint32_t>(dups.size()));
  for (auto& d : dups) {
    char lb[2];
    s16(reinterpret_cast<uint8_t*>(lb), static_cast<uint16_t>(d.size()));
    out.append(lb, 2);
    out.append(d);
  }
  return out;
}

// -- tables (catalog) ---------------------------------------------------------

constexpr uint32_t TI_SIZE = 12;  // u32 root | u64 count

TableInfo* tx_table(Txn* t, const std::string& name, bool create) {
  auto it = t->tables.find(name);
  if (it != t->tables.end()) return &it->second;
  // look up in the catalog tree of the snapshot
  Path path;
  bool exact;
  uint32_t root = t->snap.catalog_root;
  TableInfo info;
  if (root && tree_descend(t, &root, name, path, false, &exact) && exact) {
    LeafView v = leaf_view(cell_at(tx_page(t, path.back().pgno),
                                   path.back().idx));
    info.root = g32(v.payload);
    info.count = g64(v.payload + 4);
  } else if (!create) {
    return nullptr;
  }
  auto [ins, _] = t->tables.emplace(name, info);
  return &ins->second;
}

// -- high-level get/put/del over one table tree -------------------------------

// Frees any auxiliary storage (overflow chain / dup subtree) of a leaf cell.
void free_aux(Txn* t, const LeafView& v) {
  if (v.flags == L_OVERFLOW) {
    ov_free(t, g32(v.payload));
  } else if (v.flags == L_DUPTREE) {
    // free the whole subtree
    uint32_t sub = g32(v.payload);
    std::vector<uint32_t> stack{sub};
    while (!stack.empty()) {
      uint32_t pg = stack.back();
      stack.pop_back();
      if (!pg) continue;
      const uint8_t* p = tx_page(t, pg);
      if (hdr(p)->type == P_BRANCH)
        for (int i = 0; i < hdr(p)->n_cells; i++)
          stack.push_back(branch_child(cell_at(p, i)));
      tx_free(t, pg);
    }
  }
}

std::string plain_cell(Txn* t, std::string_view key, const uint8_t* val,
                       uint32_t vlen) {
  if (8 + key.size() + vlen <= MAXCELL)
    return make_leaf_cell(L_INLINE, key, vlen, val, vlen);
  uint32_t ov = ov_write(t, val, vlen);
  uint8_t pb[4];
  s32(pb, ov);
  return make_leaf_cell(L_OVERFLOW, key, vlen, pb, 4);
}

// insert into a dup subtree; returns true when a new entry was added
bool subtree_put(Txn* t, uint32_t* sub, std::string_view val) {
  Path path;
  bool exact;
  tree_descend(t, sub, val, path, *sub != 0, &exact);
  if (exact) return false;
  std::string cell = make_leaf_cell(L_INLINE, val, 0, nullptr, 0);
  if (!*sub) {
    *sub = tx_alloc(t);
    uint8_t* p = tx_writable(t, *sub);
    std::vector<std::string> cells{std::move(cell)};
    rebuild(p, P_LEAF, cells, 0, cells.size());
    return true;
  }
  leaf_put_cell(t, sub, path, std::move(cell), false);
  return true;
}

bool subtree_del(Txn* t, uint32_t* sub, std::string_view val) {
  Path path;
  bool exact;
  if (!tree_descend(t, sub, val, path, *sub != 0, &exact) || !exact)
    return false;
  tree_remove_at(t, sub, path);
  return true;
}

bool table_put(Txn* t, TableInfo* ti, std::string_view key,
               std::string_view val, bool dupsort) {
  Path path;
  bool exact;
  tree_descend(t, &ti->root, key, path, ti->root != 0, &exact);
  ti->dirty = true;
  const uint8_t* vp = reinterpret_cast<const uint8_t*>(val.data());
  uint32_t vlen = static_cast<uint32_t>(val.size());

  if (!exact) {
    std::string cell;
    if (dupsort) {
      std::vector<std::string> dups{std::string(val)};
      std::string payload = dup_pack(dups);
      cell = make_leaf_cell(L_DUPIN, key, static_cast<uint32_t>(payload.size()),
                            payload.data(), static_cast<uint32_t>(payload.size()));
    } else {
      cell = plain_cell(t, key, vp, vlen);
    }
    if (!ti->root) {
      ti->root = tx_alloc(t);
      std::vector<std::string> cells{std::move(cell)};
      rebuild(tx_writable(t, ti->root), P_LEAF, cells, 0, cells.size());
    } else {
      leaf_put_cell(t, &ti->root, path, std::move(cell), false);
    }
    ti->count += 1;
    return true;
  }

  LeafView old = leaf_view(cell_at(tx_page(t, path.back().pgno),
                                   path.back().idx));
  if (!dupsort) {
    // plain put replaces everything under the key (matches kvstore.cpp)
    uint64_t old_n = 1;
    if (old.flags == L_DUPIN)
      old_n = g32(old.payload);
    else if (old.flags == L_DUPTREE)
      old_n = g64(old.payload + 4);
    free_aux(t, old);
    leaf_put_cell(t, &ti->root, path, plain_cell(t, key, vp, vlen), true);
    ti->count += 1 - old_n;
    return true;
  }

  // dupsort insert into an existing cell
  if (old.flags == L_DUPTREE) {
    uint32_t sub = g32(old.payload);
    uint64_t cnt = g64(old.payload + 4);
    if (subtree_put(t, &sub, val)) cnt++, ti->count++;
    uint8_t pb[12];
    s32(pb, sub);
    s64(pb + 4, cnt);
    leaf_put_cell(t, &ti->root, path,
                  make_leaf_cell(L_DUPTREE, key, 12, pb, 12), true);
    return true;
  }
  std::vector<std::string> dups;
  if (old.flags == L_DUPIN) {
    dups = dup_unpack(old.payload, old.payload_sz);
  } else {  // plain value becomes the first duplicate
    std::string prior;
    if (old.flags == L_OVERFLOW) {
      ov_read(t, g32(old.payload), prior);
    } else {
      prior.assign(reinterpret_cast<const char*>(old.payload), old.vlen);
    }
    // a duplicate must fit a leaf/subtree cell; refuse the conversion of
    // an oversized plain value instead of corrupting a page
    if (8 + prior.size() > MAXCELL) return false;
    if (old.flags == L_OVERFLOW) free_aux(t, old);
    dups.push_back(std::move(prior));
  }
  auto pos = std::lower_bound(dups.begin(), dups.end(), std::string(val));
  if (pos != dups.end() && *pos == val) {
    return true;  // already present
  }
  dups.insert(pos, std::string(val));
  ti->count += 1;
  std::string payload = dup_pack(dups);
  if (8 + key.size() + payload.size() <= MAXCELL &&
      payload.size() <= DUP_SPILL + 4) {
    leaf_put_cell(t, &ti->root, path,
                  make_leaf_cell(L_DUPIN, key,
                                 static_cast<uint32_t>(payload.size()),
                                 payload.data(),
                                 static_cast<uint32_t>(payload.size())),
                  true);
  } else {  // spill to a subtree
    uint32_t sub = 0;
    for (auto& d : dups) subtree_put(t, &sub, d);
    uint8_t pb[12];
    s32(pb, sub);
    s64(pb + 4, dups.size());
    leaf_put_cell(t, &ti->root, path,
                  make_leaf_cell(L_DUPTREE, key, 12, pb, 12), true);
  }
  return true;
}

bool table_del(Txn* t, TableInfo* ti, std::string_view key,
               const std::string* val) {
  Path path;
  bool exact;
  if (!tree_descend(t, &ti->root, key, path, ti->root != 0, &exact) || !exact)
    return false;
  LeafView v = leaf_view(cell_at(tx_page(t, path.back().pgno),
                                 path.back().idx));
  uint64_t n = (v.flags == L_DUPIN)     ? g32(v.payload)
               : (v.flags == L_DUPTREE) ? g64(v.payload + 4)
                                        : 1;
  if (val == nullptr) {
    free_aux(t, v);
    tree_remove_at(t, &ti->root, path);
    ti->count -= n;
    ti->dirty = true;
    return true;
  }
  if (v.flags == L_DUPTREE) {
    uint32_t sub = g32(v.payload);
    if (!subtree_del(t, &sub, *val)) return false;
    ti->count -= 1;
    ti->dirty = true;
    if (n - 1 == 0 || sub == 0) {
      tree_remove_at(t, &ti->root, path);
    } else {
      uint8_t pb[12];
      s32(pb, sub);
      s64(pb + 4, n - 1);
      leaf_put_cell(t, &ti->root, path,
                    make_leaf_cell(L_DUPTREE, key, 12, pb, 12), true);
    }
    return true;
  }
  std::vector<std::string> dups;
  if (v.flags == L_DUPIN) {
    dups = dup_unpack(v.payload, v.payload_sz);
  } else {
    std::string prior;
    if (v.flags == L_OVERFLOW)
      ov_read(t, g32(v.payload), prior);
    else
      prior.assign(reinterpret_cast<const char*>(v.payload), v.vlen);
    dups.push_back(std::move(prior));
  }
  auto pos = std::lower_bound(dups.begin(), dups.end(), *val);
  if (pos == dups.end() || *pos != *val) return false;
  dups.erase(pos);
  ti->count -= 1;
  ti->dirty = true;
  if (dups.empty()) {
    free_aux(t, v);
    tree_remove_at(t, &ti->root, path);
    return true;
  }
  std::string payload = dup_pack(dups);
  free_aux(t, v);
  leaf_put_cell(t, &ti->root, path,
                make_leaf_cell(L_DUPIN, key,
                               static_cast<uint32_t>(payload.size()),
                               payload.data(),
                               static_cast<uint32_t>(payload.size())),
                true);
  return true;
}

// -- env open/commit ----------------------------------------------------------

bool read_meta(Env* env, int slot, Meta* out) {
  Meta m;
  if (pread(env->fd, &m, sizeof(m), slot * PAGE) != sizeof(m)) return false;
  if (m.magic != MAGIC || m.version != VERSION) return false;
  if (meta_sum(m) != m.checksum) return false;
  *out = m;
  return true;
}

bool write_meta(Env* env, const Meta& m) {
  Meta out = m;
  out.checksum = meta_sum(out);
  int slot = static_cast<int>(m.txnid & 1);
  if (pwrite(env->fd, &out, sizeof(out), slot * PAGE) != sizeof(out))
    return false;
  return fdatasync(env->fd) == 0;
}

Env* env_open(const std::string& dir) {
  std::string path = dir + "/data.rtpg";
  int fd = ::open(path.c_str(), O_RDWR | O_CREAT | O_CLOEXEC, 0644);
  if (fd < 0) return nullptr;
  auto env = std::make_unique<Env>();
  env->fd = fd;
  env->dir = dir;
  struct stat st{};
  fstat(fd, &st);
  if (st.st_size < static_cast<off_t>(2 * PAGE)) {
    if (ftruncate(fd, 2 * PAGE) != 0) return nullptr;
    Meta m{};
    m.magic = MAGIC;
    m.version = VERSION;
    m.txnid = 0;
    m.n_pages = 2;
    if (!write_meta(env.get(), m)) return nullptr;
    env->meta = m;
  } else {
    Meta m0, m1;
    bool ok0 = read_meta(env.get(), 0, &m0);
    bool ok1 = read_meta(env.get(), 1, &m1);
    if (!ok0 && !ok1) return nullptr;
    env->meta = (!ok1 || (ok0 && m0.txnid > m1.txnid)) ? m0 : m1;
  }
  env->map = static_cast<uint8_t*>(
      mmap(nullptr, MAPSIZE, PROT_READ, MAP_SHARED, fd, 0));
  if (env->map == MAP_FAILED) return nullptr;
  // load the persisted free list (no readers at open: all reusable)
  uint32_t pg = env->meta.freelist_head;
  while (pg) {
    const uint8_t* p = env->map + static_cast<uint64_t>(pg) * PAGE;
    uint16_t n = g16(p + 2);
    for (uint16_t i = 0; i < n; i++)
      env->reusable.push_back(g32(p + 8 + 4 * i));
    env->freelist_pages.push_back(pg);
    pg = g32(p + 4);
  }
  return env.release();
}

int tx_commit(Txn* t) {
  Env* env = t->env;
  // 1. flush table-info updates into the catalog tree
  for (auto& [name, info] : t->tables) {
    if (!info.dirty) continue;
    Path path;
    bool exact;
    uint32_t root = t->snap.catalog_root;
    tree_descend(t, &root, name, path, root != 0, &exact);
    uint8_t pb[TI_SIZE];
    s32(pb, info.root);
    s64(pb + 4, info.count);
    std::string cell = make_leaf_cell(L_INLINE, name, TI_SIZE, pb, TI_SIZE);
    if (!root) {
      root = tx_alloc(t);
      std::vector<std::string> cells{std::move(cell)};
      rebuild(tx_writable(t, root), P_LEAF, cells, 0, cells.size());
    } else {
      leaf_put_cell(t, &root, path, std::move(cell), exact);
    }
    t->snap.catalog_root = root;
  }
  // 2. free candidates for the NEXT version: data pages freed this txn plus
  //    the chain pages of the free list we are about to replace
  std::vector<uint32_t> newly_freed = t->freed;
  std::vector<uint32_t> persist;
  {
    std::lock_guard<std::mutex> g(env->state_mu);
    newly_freed.insert(newly_freed.end(), env->freelist_pages.begin(),
                       env->freelist_pages.end());
    persist = env->reusable;
    for (auto& [_, pages] : env->pending)
      persist.insert(persist.end(), pages.begin(), pages.end());
  }
  persist.insert(persist.end(), newly_freed.begin(), newly_freed.end());
  persist.insert(persist.end(), t->recycle.begin(), t->recycle.end());
  // 3. serialize the free list into fresh chain pages (allocated at the end
  //    so they never collide with any referenced page)
  constexpr uint32_t PER = (PAGE - 8) / 4;
  std::vector<uint32_t> chain;
  uint64_t nchain = (persist.size() + PER - 1) / PER;
  for (uint64_t i = 0; i < nchain; i++)
    chain.push_back(static_cast<uint32_t>(t->next_page++));
  std::vector<std::unique_ptr<uint8_t[]>> chain_bufs;
  for (uint64_t i = 0; i < nchain; i++) {
    auto buf = std::make_unique<uint8_t[]>(PAGE);
    memset(buf.get(), 0, PAGE);
    buf[0] = P_FREE;
    uint32_t start = static_cast<uint32_t>(i * PER);
    uint32_t n = std::min<uint32_t>(PER,
                                    static_cast<uint32_t>(persist.size()) - start);
    s16(buf.get() + 2, static_cast<uint16_t>(n));
    s32(buf.get() + 4, i + 1 < nchain ? chain[i + 1] : 0);
    for (uint32_t j = 0; j < n; j++)
      s32(buf.get() + 8 + 4 * j, persist[start + j]);
    chain_bufs.push_back(std::move(buf));
  }
  // 4. grow the file, write everything, sync, flip the meta
  if (ftruncate(env->fd, static_cast<off_t>(t->next_page * PAGE)) != 0)
    return -1;
  for (auto& [pgno, buf] : t->dirty) {
    if (pwrite(env->fd, buf.get(), PAGE,
               static_cast<off_t>(pgno) * PAGE) != PAGE)
      return -1;
  }
  for (uint64_t i = 0; i < nchain; i++) {
    if (pwrite(env->fd, chain_bufs[i].get(), PAGE,
               static_cast<off_t>(chain[i]) * PAGE) != PAGE)
      return -1;
  }
  if (fdatasync(env->fd) != 0) return -1;
  Meta m = t->snap;
  m.txnid += 1;
  m.n_pages = t->next_page;
  m.freelist_head = chain.empty() ? 0 : chain[0];
  m.freelist_len = persist.size();
  if (!write_meta(env, m)) return -1;
  {
    std::lock_guard<std::mutex> g(env->state_mu);
    env->meta = m;
    if (!newly_freed.empty()) env->pending.emplace_back(m.txnid, newly_freed);
    env->reusable.insert(env->reusable.end(), t->recycle.begin(),
                         t->recycle.end());
    env->freelist_pages = chain;
    drain_pending(env);
  }
  return 0;
}

// -- cursors ------------------------------------------------------------------
// Live-view cursors: every positioning/step operation resolves against the
// txn's current tree (dirty pages included), keyed by the cursor's (key,
// duplicate) position. This matches the MemDb semantics the contract tests
// pin down: a write txn's own mutations are visible to pre-existing cursors.

struct Cur {
  Txn* txn;
  std::string table;
  enum State : uint8_t { UNPOS, POS, EXH } state = UNPOS;
  std::string key;     // current key
  std::string dupval;  // current duplicate value
  std::string kbuf, vbuf;
};

// resolve the dup list of a leaf cell into (count); fills vector for inline
struct DupPos {
  bool is_tree;
  uint32_t sub;
  std::vector<std::string> inl;
  uint64_t count;
};

bool cell_dups(Txn* t, const LeafView& v, DupPos* out) {
  out->is_tree = false;
  out->sub = 0;
  out->inl.clear();
  if (v.flags == L_DUPIN) {
    out->inl = dup_unpack(v.payload, v.payload_sz);
    out->count = out->inl.size();
    return true;
  }
  if (v.flags == L_DUPTREE) {
    out->is_tree = true;
    out->sub = g32(v.payload);
    out->count = g64(v.payload + 4);
    return true;
  }
  // plain value acts as a single-element dup list
  if (v.flags == L_OVERFLOW) {
    std::string s;
    ov_read(t, g32(v.payload), s);
    out->inl.push_back(std::move(s));
  } else {
    out->inl.emplace_back(reinterpret_cast<const char*>(v.payload), v.vlen);
  }
  out->count = 1;
  return true;
}

int cur_emit(Cur* c, const uint8_t** k, uint32_t* kl, const uint8_t** v,
             uint32_t* vl) {
  c->kbuf = c->key;
  c->vbuf = c->dupval;
  *k = reinterpret_cast<const uint8_t*>(c->kbuf.data());
  *kl = static_cast<uint32_t>(c->kbuf.size());
  *v = reinterpret_cast<const uint8_t*>(c->vbuf.data());
  *vl = static_cast<uint32_t>(c->vbuf.size());
  return 1;
}

// subtree navigation: smallest value strictly greater than `after`
// (or >= `from` when ge), largest value strictly less, first, last
bool subtree_seek(Txn* t, uint32_t sub, std::string_view from, bool strict,
                  std::string* out) {
  Path path;
  bool exact;
  if (!sub) return false;
  tree_descend(t, &sub, from, path, false, &exact);
  if (exact && strict) {
    if (!path_step(t, path, +1)) return false;
  } else if (!exact) {
    // lower_bound position may be one past the leaf's cells
    const uint8_t* leaf = tx_page(t, path.back().pgno);
    if (path.back().idx >= hdr(leaf)->n_cells) {
      path.back().idx = hdr(leaf)->n_cells - 1;
      if (!path_step(t, path, +1)) return false;
    }
  }
  LeafView v =
      leaf_view(cell_at(tx_page(t, path.back().pgno), path.back().idx));
  *out = std::string(v.key);
  return true;
}

bool subtree_prev(Txn* t, uint32_t sub, std::string_view before,
                  std::string* out) {
  Path path;
  bool exact;
  if (!sub) return false;
  tree_descend(t, &sub, before, path, false, &exact);
  // position is lower_bound(before); the predecessor is one step back
  if (!path_step(t, path, -1)) return false;
  LeafView v =
      leaf_view(cell_at(tx_page(t, path.back().pgno), path.back().idx));
  *out = std::string(v.key);
  return true;
}

bool subtree_edge(Txn* t, uint32_t sub, bool last, std::string* out) {
  Path path;
  if (!sub) return false;
  descend_edge(t, sub, last, path);
  if (path.empty()) return false;
  LeafView v =
      leaf_view(cell_at(tx_page(t, path.back().pgno), path.back().idx));
  *out = std::string(v.key);
  return true;
}

// position the cursor on (key-at-path, first-or-last dup)
bool cur_land(Cur* c, Path& path, bool last_dup) {
  Txn* t = c->txn;
  LeafView v =
      leaf_view(cell_at(tx_page(t, path.back().pgno), path.back().idx));
  c->key = std::string(v.key);
  DupPos dp;
  cell_dups(t, v, &dp);
  if (dp.is_tree) {
    if (!subtree_edge(t, dp.sub, last_dup, &c->dupval)) return false;
  } else {
    if (dp.inl.empty()) return false;
    c->dupval = last_dup ? dp.inl.back() : dp.inl.front();
  }
  c->state = Cur::POS;
  return true;
}

// find the cursor's key cell in the live tree; nullptr if the key vanished
bool cur_find(Cur* c, Path& path, LeafView* v) {
  Txn* t = c->txn;
  TableInfo* ti = tx_table(t, c->table, false);
  if (!ti || !ti->root) return false;
  uint32_t root = ti->root;
  bool exact;
  tree_descend(t, &root, c->key, path, false, &exact);
  if (!exact) return false;
  *v = leaf_view(cell_at(tx_page(t, path.back().pgno), path.back().idx));
  return true;
}

}  // namespace

extern "C" {

void* rtpg_open(const char* dir) {
  if (!dir || !*dir) return nullptr;  // paged engine is persistent-only
  return env_open(dir);
}

void rtpg_close(void* envp) { delete static_cast<Env*>(envp); }

int rtpg_snapshot(void* envp) {  // durability point; commits already sync
  auto env = static_cast<Env*>(envp);
  return fdatasync(env->fd) == 0 ? 0 : -1;
}

int rtpg_sync(void* envp) {
  auto env = static_cast<Env*>(envp);
  return fdatasync(env->fd) == 0 ? 0 : -1;
}

void* rtpg_txn_begin(void* envp, int write) {
  auto env = static_cast<Env*>(envp);
  auto txn = new Txn();
  txn->env = env;
  txn->write = write != 0;
  if (write) {
    if (env->writer_owner == std::this_thread::get_id()) {
      delete txn;
      return nullptr;  // nested write txn on one thread
    }
    env->writer_mu.lock();
    env->writer_owner = std::this_thread::get_id();
  }
  {
    std::lock_guard<std::mutex> g(env->state_mu);
    txn->snap = env->meta;
    if (!write) env->readers.insert(txn->snap.txnid);
  }
  txn->next_page = txn->snap.n_pages;
  return txn;
}

static void reader_end(Txn* txn) {
  std::lock_guard<std::mutex> g(txn->env->state_mu);
  auto it = txn->env->readers.find(txn->snap.txnid);
  if (it != txn->env->readers.end()) txn->env->readers.erase(it);
}

int rtpg_put(void* txnp, const char* table, const uint8_t* key, uint32_t klen,
             const uint8_t* val, uint32_t vlen, int dupsort) {
  auto txn = static_cast<Txn*>(txnp);
  std::lock_guard<std::recursive_mutex> op_guard(txn->op_mu);
  if (!txn->write || klen > MAXKEY) return -1;
  if (dupsort && 8 + klen + vlen > MAXCELL) return -1;  // dup values stay small
  TableInfo* ti = tx_table(txn, table, true);
  return table_put(txn, ti,
                   std::string_view(reinterpret_cast<const char*>(key), klen),
                   std::string_view(
                       reinterpret_cast<const char*>(val ? val : key),
                       val ? vlen : 0),
                   dupsort != 0)
             ? 0
             : -1;
}

int rtpg_del(void* txnp, const char* table, const uint8_t* key, uint32_t klen,
             const uint8_t* val, uint32_t vlen, int have_val) {
  auto txn = static_cast<Txn*>(txnp);
  std::lock_guard<std::recursive_mutex> op_guard(txn->op_mu);
  if (!txn->write) return 0;
  TableInfo* ti = tx_table(txn, table, false);
  if (!ti) return 0;
  std::string v(reinterpret_cast<const char*>(val ? val : key),
                val ? vlen : 0);
  return table_del(txn, ti,
                   std::string_view(reinterpret_cast<const char*>(key), klen),
                   have_val ? &v : nullptr)
             ? 1
             : 0;
}

int rtpg_clear(void* txnp, const char* table) {
  auto txn = static_cast<Txn*>(txnp);
  std::lock_guard<std::recursive_mutex> op_guard(txn->op_mu);
  if (!txn->write) return -1;
  TableInfo* ti = tx_table(txn, table, false);
  if (!ti || !ti->root) return 0;
  // free every page of the tree (and aux chains/subtrees)
  std::vector<uint32_t> stack{ti->root};
  while (!stack.empty()) {
    uint32_t pg = stack.back();
    stack.pop_back();
    const uint8_t* p = tx_page(txn, pg);
    if (hdr(p)->type == P_BRANCH) {
      for (int i = 0; i < hdr(p)->n_cells; i++)
        stack.push_back(branch_child(cell_at(p, i)));
    } else {
      for (int i = 0; i < hdr(p)->n_cells; i++) {
        LeafView v = leaf_view(cell_at(p, i));
        free_aux(txn, v);
      }
    }
    tx_free(txn, pg);
  }
  ti->root = 0;
  ti->count = 0;
  ti->dirty = true;
  return 0;
}

int rtpg_get(void* txnp, const char* table, const uint8_t* key, uint32_t klen,
             const uint8_t** out, uint32_t* out_len) {
  auto txn = static_cast<Txn*>(txnp);
  std::lock_guard<std::recursive_mutex> op_guard(txn->op_mu);
  TableInfo* ti = tx_table(txn, table, false);
  if (!ti || !ti->root) return 0;
  uint32_t root = ti->root;
  Path path;
  bool exact;
  tree_descend(txn, &root,
               std::string_view(reinterpret_cast<const char*>(key), klen),
               path, false, &exact);
  if (!exact) return 0;
  LeafView v =
      leaf_view(cell_at(tx_page(txn, path.back().pgno), path.back().idx));
  if (v.flags == L_INLINE) {
    txn->valbuf.assign(reinterpret_cast<const char*>(v.payload), v.vlen);
  } else if (v.flags == L_OVERFLOW) {
    ov_read(txn, g32(v.payload), txn->valbuf);
  } else {  // dup cell: return the first duplicate
    DupPos dp;
    cell_dups(txn, v, &dp);
    if (dp.is_tree) {
      if (!subtree_edge(txn, dp.sub, false, &txn->valbuf)) return 0;
    } else {
      if (dp.inl.empty()) return 0;
      txn->valbuf = dp.inl.front();
    }
  }
  *out = reinterpret_cast<const uint8_t*>(txn->valbuf.data());
  *out_len = static_cast<uint32_t>(txn->valbuf.size());
  return 1;
}

uint64_t rtpg_entry_count(void* txnp, const char* table) {
  auto txn = static_cast<Txn*>(txnp);
  std::lock_guard<std::recursive_mutex> op_guard(txn->op_mu);
  TableInfo* ti = tx_table(txn, table, false);
  return ti ? ti->count : 0;
}

int rtpg_commit(void* txnp) {
  auto txn = static_cast<Txn*>(txnp);
  int rc = 0;
  if (txn->write) {
    rc = tx_commit(txn);
    txn->env->writer_owner = std::thread::id{};
    txn->env->writer_mu.unlock();
  } else {
    reader_end(txn);
  }
  delete txn;
  return rc;
}

void rtpg_abort(void* txnp) {
  auto txn = static_cast<Txn*>(txnp);
  if (txn->write) {
    std::lock_guard<std::mutex> g(txn->env->state_mu);
    txn->env->reusable.insert(txn->env->reusable.end(),
                              txn->took_reusable.begin(),
                              txn->took_reusable.end());
    txn->env->writer_owner = std::thread::id{};
    txn->env->writer_mu.unlock();
  } else {
    reader_end(txn);
  }
  delete txn;
}

void* rtpg_cursor(void* txnp, const char* table) {
  auto cur = new Cur();
  cur->txn = static_cast<Txn*>(txnp);
  cur->table = table;
  return cur;
}

void rtpg_cursor_close(void* curp) { delete static_cast<Cur*>(curp); }

int rtpg_cursor_first(void* curp, const uint8_t** k, uint32_t* kl,
                      const uint8_t** v, uint32_t* vl) {
  auto c = static_cast<Cur*>(curp);
  std::lock_guard<std::recursive_mutex> op_guard(c->txn->op_mu);
  TableInfo* ti = tx_table(c->txn, c->table, false);
  if (!ti || !ti->root) {
    c->state = Cur::EXH;
    return 0;
  }
  Path path;
  descend_edge(c->txn, ti->root, false, path);
  if (path.empty() || !cur_land(c, path, false)) {
    c->state = Cur::EXH;
    return 0;
  }
  return cur_emit(c, k, kl, v, vl);
}

int rtpg_cursor_last(void* curp, const uint8_t** k, uint32_t* kl,
                     const uint8_t** v, uint32_t* vl) {
  auto c = static_cast<Cur*>(curp);
  std::lock_guard<std::recursive_mutex> op_guard(c->txn->op_mu);
  TableInfo* ti = tx_table(c->txn, c->table, false);
  if (!ti || !ti->root) {
    c->state = Cur::EXH;
    return 0;
  }
  Path path;
  descend_edge(c->txn, ti->root, true, path);
  if (path.empty() || !cur_land(c, path, true)) {
    c->state = Cur::EXH;
    return 0;
  }
  return cur_emit(c, k, kl, v, vl);
}

int rtpg_cursor_seek(void* curp, const uint8_t* key, uint32_t klen, int exact,
                     const uint8_t** k, uint32_t* kl, const uint8_t** v,
                     uint32_t* vl) {
  auto c = static_cast<Cur*>(curp);
  std::lock_guard<std::recursive_mutex> op_guard(c->txn->op_mu);
  c->state = Cur::EXH;
  TableInfo* ti = tx_table(c->txn, c->table, false);
  if (!ti || !ti->root) return 0;
  uint32_t root = ti->root;
  Path path;
  bool ex;
  tree_descend(c->txn, &root,
               std::string_view(reinterpret_cast<const char*>(key), klen),
               path, false, &ex);
  if (exact && !ex) return 0;
  if (!ex) {
    // lower_bound may point past the leaf's last cell: advance
    const uint8_t* leaf = tx_page(c->txn, path.back().pgno);
    if (path.back().idx >= hdr(leaf)->n_cells) {
      path.back().idx = hdr(leaf)->n_cells - 1;
      if (!path_step(c->txn, path, +1)) return 0;
    }
  }
  if (!cur_land(c, path, false)) return 0;
  return cur_emit(c, k, kl, v, vl);
}

int rtpg_cursor_next(void* curp, int skip_dups, const uint8_t** k,
                     uint32_t* kl, const uint8_t** v, uint32_t* vl) {
  auto c = static_cast<Cur*>(curp);
  std::lock_guard<std::recursive_mutex> op_guard(c->txn->op_mu);
  if (c->state == Cur::EXH) return 0;
  if (c->state == Cur::UNPOS) return rtpg_cursor_first(curp, k, kl, v, vl);
  Txn* t = c->txn;
  Path path;
  LeafView lv;
  bool have = cur_find(c, path, &lv);
  if (have && !skip_dups) {
    DupPos dp;
    cell_dups(t, lv, &dp);
    if (dp.is_tree) {
      std::string nxt;
      if (subtree_seek(t, dp.sub, c->dupval, true, &nxt)) {
        c->dupval = nxt;
        return cur_emit(c, k, kl, v, vl);
      }
    } else {
      auto pos = std::upper_bound(dp.inl.begin(), dp.inl.end(), c->dupval);
      if (pos != dp.inl.end()) {
        c->dupval = *pos;
        return cur_emit(c, k, kl, v, vl);
      }
    }
  }
  // move to the next key
  TableInfo* ti = tx_table(t, c->table, false);
  if (!ti || !ti->root) {
    c->state = Cur::EXH;
    return 0;
  }
  uint32_t root = ti->root;
  bool ex;
  tree_descend(t, &root, c->key, path, false, &ex);
  if (ex) {
    if (!path_step(t, path, +1)) {
      c->state = Cur::EXH;
      return 0;
    }
  } else {
    // current key vanished: lower_bound is already the next entry
    const uint8_t* leaf = tx_page(t, path.back().pgno);
    if (path.back().idx >= hdr(leaf)->n_cells) {
      path.back().idx = hdr(leaf)->n_cells - 1;
      if (!path_step(t, path, +1)) {
        c->state = Cur::EXH;
        return 0;
      }
    }
  }
  if (!cur_land(c, path, false)) {
    c->state = Cur::EXH;
    return 0;
  }
  return cur_emit(c, k, kl, v, vl);
}

int rtpg_cursor_prev(void* curp, const uint8_t** k, uint32_t* kl,
                     const uint8_t** v, uint32_t* vl) {
  auto c = static_cast<Cur*>(curp);
  std::lock_guard<std::recursive_mutex> op_guard(c->txn->op_mu);
  if (c->state == Cur::UNPOS) return 0;
  if (c->state == Cur::EXH) return rtpg_cursor_last(curp, k, kl, v, vl);
  Txn* t = c->txn;
  Path path;
  LeafView lv;
  bool have = cur_find(c, path, &lv);
  if (have) {
    DupPos dp;
    cell_dups(t, lv, &dp);
    if (dp.is_tree) {
      std::string prv;
      if (subtree_prev(t, dp.sub, c->dupval, &prv)) {
        c->dupval = prv;
        return cur_emit(c, k, kl, v, vl);
      }
    } else {
      auto pos = std::lower_bound(dp.inl.begin(), dp.inl.end(), c->dupval);
      if (pos != dp.inl.begin()) {
        c->dupval = *(pos - 1);
        return cur_emit(c, k, kl, v, vl);
      }
    }
  }
  // move to the previous key (lower_bound(cur_key) - 1 in the live tree)
  TableInfo* ti = tx_table(t, c->table, false);
  if (!ti || !ti->root) {
    c->state = Cur::UNPOS;
    return 0;
  }
  uint32_t root = ti->root;
  bool ex;
  tree_descend(t, &root, c->key, path, false, &ex);
  if (!path_step(t, path, -1)) {
    c->state = Cur::UNPOS;
    return 0;
  }
  if (!cur_land(c, path, true)) {
    c->state = Cur::UNPOS;
    return 0;
  }
  return cur_emit(c, k, kl, v, vl);
}

int rtpg_cursor_next_dup(void* curp, const uint8_t** k, uint32_t* kl,
                         const uint8_t** v, uint32_t* vl) {
  auto c = static_cast<Cur*>(curp);
  std::lock_guard<std::recursive_mutex> op_guard(c->txn->op_mu);
  if (c->state != Cur::POS) return 0;
  Path path;
  LeafView lv;
  if (!cur_find(c, path, &lv)) return 0;
  DupPos dp;
  cell_dups(c->txn, lv, &dp);
  if (dp.is_tree) {
    std::string nxt;
    if (!subtree_seek(c->txn, dp.sub, c->dupval, true, &nxt)) return 0;
    c->dupval = nxt;
    return cur_emit(c, k, kl, v, vl);
  }
  auto pos = std::upper_bound(dp.inl.begin(), dp.inl.end(), c->dupval);
  if (pos == dp.inl.end()) return 0;
  c->dupval = *pos;
  return cur_emit(c, k, kl, v, vl);
}

int rtpg_cursor_seek_dup(void* curp, const uint8_t* key, uint32_t klen,
                         const uint8_t* sub, uint32_t slen, const uint8_t** k,
                         uint32_t* kl, const uint8_t** v, uint32_t* vl) {
  auto c = static_cast<Cur*>(curp);
  std::lock_guard<std::recursive_mutex> op_guard(c->txn->op_mu);
  c->state = Cur::EXH;
  c->key.assign(reinterpret_cast<const char*>(key), klen);
  Path path;
  LeafView lv;
  if (!cur_find(c, path, &lv)) return 0;
  DupPos dp;
  cell_dups(c->txn, lv, &dp);
  std::string target(reinterpret_cast<const char*>(sub), slen);
  if (dp.is_tree) {
    std::string got;
    if (!subtree_seek(c->txn, dp.sub, target, false, &got)) return 0;
    c->dupval = got;
  } else {
    auto pos = std::lower_bound(dp.inl.begin(), dp.inl.end(), target);
    if (pos == dp.inl.end()) return 0;
    c->dupval = *pos;
  }
  c->state = Cur::POS;
  return cur_emit(c, k, kl, v, vl);
}

}  // extern "C"
