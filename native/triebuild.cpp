// Native trie-structure builder for the fused device commit ("turbo path").
//
// The round-1 committer spent ~9 us/node of Python on structure + RLP
// template building — the host-side wall the TPU cannot fix (round-1
// VERDICT, weak #1/#3). This C++ sweep does all per-node work at memcpy
// speed and emits flat numpy-ready arrays grouped by trie depth level:
//
//   - PACKED rows (leaves, extensions, and the rare branch with an inline
//     child): tightly concatenated RLP template bytes + row offsets +
//     digest-splice holes. No padding crosses the host->device wire; the
//     device unpacks rows by gather (reth_tpu/ops/fused_commit.py).
//   - BITMAP rows (branches whose 16 children are all hashed — the
//     overwhelming majority in a secure trie): just a 2-byte state mask +
//     child (row, nibble, src-slot) triples. The device reconstructs the
//     full branch RLP (header f9 xx xx, 33-byte refs, empty-slot 0x80,
//     empty value) from the mask alone — a ~250x H2D reduction per branch.
//
// Layout rules mirror reth_tpu/trie/node.py (yellow-paper MPT encodings)
// and the structure recursion mirrors trie/committer.py::_build; parity is
// pinned by tests/test_turbo_commit.py. Reference analogue: the alloy-trie
// HashBuilder + StateRoot walk (reference crates/trie/trie/src/trie.rs:32)
// re-designed as a host-side array producer for a device hashing plane.
//
// Secure-trie keys only: every key is exactly 32 bytes (64 nibbles), as
// produced by keccak256(address|slot) — the MerkleStage full-rebuild shape
// (reference crates/stages/stages/src/stages/merkle.rs:184).

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr int RATE = 136;
constexpr int NIBS = 64;

struct Hole {           // digest splice target inside a packed row
    int32_t row;
    int32_t off;        // byte offset within the row's RLP
    int32_t src;        // digest-buffer slot of the child
};

struct Child {          // bitmap-branch child
    int32_t row;
    int32_t nib;
    int32_t src;
};

struct Level {
    // packed group
    std::vector<uint8_t> bytes;
    std::vector<uint32_t> row_off;   // size rows+1
    std::vector<int32_t> row_slot;
    std::vector<Hole> holes;
    // bitmap group
    std::vector<uint16_t> masks;
    std::vector<int32_t> bmp_slot;
    std::vector<Child> children;
};

struct BranchMeta {      // TrieUpdates record (reference BranchNodeCompact)
    uint32_t job;
    uint32_t rep_key;    // path = keys[rep_key][:depth]
    uint16_t depth;
    uint16_t state_mask;
    uint16_t tree_mask;
    uint16_t hash_mask;
    int32_t child_slot[16];  // slot when hashed, -1 otherwise
};

// A finalized child reference flowing up the recursion.
struct Ref {
    int32_t slot;              // >0 when hashed
    uint32_t inline_off;       // into scratch, when slot == 0
    uint32_t inline_len;
    bool has_branch;           // subtree contains a branch (tree_mask)
};

struct Build {
    const uint8_t* keys;
    const uint8_t* values;
    const uint64_t* val_off;
    uint32_t job;
    bool collect_meta;
    std::vector<Level> levels{NIBS + 1};
    std::vector<uint8_t> scratch;          // inline-node RLP bytes
    std::vector<BranchMeta> meta;
    int32_t next_slot = 1;                 // 0 reserved dummy
    int err = 0;

    inline uint8_t nib(uint64_t key, int k) const {
        uint8_t b = keys[key * 32 + (k >> 1)];
        return (k & 1) ? (b & 0xF) : (b >> 4);
    }

    // RLP list header for a payload of n bytes, appended to out.
    static void list_header(std::vector<uint8_t>& out, size_t n) {
        if (n <= 55) {
            out.push_back(uint8_t(0xC0 + n));
        } else if (n <= 0xFF) {
            out.push_back(0xF8);
            out.push_back(uint8_t(n));
        } else {
            out.push_back(0xF9);
            out.push_back(uint8_t(n >> 8));
            out.push_back(uint8_t(n & 0xFF));
        }
    }

    // RLP string encoding of n bytes appended to out (single byte < 0x80
    // self-encodes; the leaf value is a string item inside the node list).
    // Returns false for n > 0xFFFF: state-trie leaf values are bounded
    // (storage <= 33 B, account RLP ~110 B), so outsized values signal a
    // caller error — reported via err=4 rather than a silently wrong root.
    static bool str_item(std::vector<uint8_t>& out, const uint8_t* v, size_t n) {
        if (n == 1 && v[0] < 0x80) {
            out.push_back(v[0]);
            return true;
        }
        if (n <= 55) {
            out.push_back(uint8_t(0x80 + n));
        } else if (n <= 0xFF) {
            out.push_back(0xB8);
            out.push_back(uint8_t(n));
        } else if (n <= 0xFFFF) {
            out.push_back(0xB9);
            out.push_back(uint8_t(n >> 8));
            out.push_back(uint8_t(n & 0xFF));
        } else {
            return false;
        }
        out.insert(out.end(), v, v + n);
        return true;
    }

    // hex-prefix encoding of nibbles key[from..64) appended to out,
    // including its RLP string header. leaf => flag 0x20.
    static void path_enc(std::vector<uint8_t>& out, const Build& b, uint64_t key,
                         int from, int to, bool leaf) {
        int n = to - from;
        int enc_len = 1 + n / 2;
        uint8_t first = leaf ? 0x20 : 0x00;
        if (n & 1) first |= 0x10 | b.nib(key, from++);
        // RLP string header (enc_len 1 with byte < 0x80 self-encodes)
        if (enc_len > 1) out.push_back(uint8_t(0x80 + enc_len));
        out.push_back(first);
        for (int k = from; k < to; k += 2)
            out.push_back(uint8_t((b.nib(key, k) << 4) | b.nib(key, k + 1)));
    }

    // Finish a node whose RLP template (holes zero-filled at hole_offs) is
    // in tmp: route to the level collectors or the inline scratch.
    Ref emit(int at_depth, std::vector<uint8_t>& tmp,
             const std::vector<Hole>& node_holes, bool has_branch) {
        Ref r{};
        r.has_branch = has_branch;
        if (tmp.size() < 32) {
            r.inline_off = uint32_t(scratch.size());
            r.inline_len = uint32_t(tmp.size());
            scratch.insert(scratch.end(), tmp.begin(), tmp.end());
            return r;
        }
        Level& lv = levels[at_depth];
        if (lv.row_off.empty()) lv.row_off.push_back(0);
        int32_t row = int32_t(lv.row_off.size()) - 1;
        r.slot = next_slot++;
        lv.bytes.insert(lv.bytes.end(), tmp.begin(), tmp.end());
        lv.row_off.push_back(uint32_t(lv.bytes.size()));
        lv.row_slot.push_back(r.slot);
        for (Hole h : node_holes) {
            h.row = row;
            lv.holes.push_back(h);
        }
        return r;
    }

    // Build the subtree for keys [lo, hi) sharing the first `depth` nibbles;
    // the node sits at trie position `at_depth` nibbles deep.
    Ref build(uint64_t lo, uint64_t hi, int depth, int at_depth) {
        if (err) return Ref{};
        if (hi - lo == 1) {  // leaf
            std::vector<uint8_t> payload;
            path_enc(payload, *this, lo, depth, NIBS, true);
            if (!str_item(payload, values + val_off[lo], val_off[lo + 1] - val_off[lo])) {
                err = 4;  // oversized leaf value
                return Ref{};
            }
            std::vector<uint8_t> tmp;
            list_header(tmp, payload.size());
            tmp.insert(tmp.end(), payload.begin(), payload.end());
            std::vector<Hole> none;
            return emit(at_depth, tmp, none, false);
        }
        // common prefix of first & last key below depth (sorted => group cpl)
        int cpl = 0;
        while (depth + cpl < NIBS && nib(lo, depth + cpl) == nib(hi - 1, depth + cpl))
            cpl++;
        if (depth + cpl >= NIBS) {  // duplicate keys
            err = 2;
            return Ref{};
        }
        if (cpl > 0) {  // extension wrapping the branch below
            Ref c = build(lo, hi, depth + cpl, at_depth + cpl);
            if (err) return Ref{};
            std::vector<uint8_t> payload;
            std::vector<Hole> holes;
            path_enc(payload, *this, lo, depth, depth + cpl, false);
            if (c.slot > 0) {
                payload.push_back(0xA0);
                holes.push_back(Hole{0, 0, c.slot});  // offset fixed below
                payload.insert(payload.end(), 32, 0);
            } else {
                payload.insert(payload.end(), scratch.begin() + c.inline_off,
                               scratch.begin() + c.inline_off + c.inline_len);
            }
            std::vector<uint8_t> tmp;
            list_header(tmp, payload.size());
            // fix hole offsets: header + position within payload
            if (!holes.empty()) {
                // digest sits right after the 0xA0 marker near the end
                holes[0].off = int32_t(tmp.size() + payload.size() - 32);
            }
            tmp.insert(tmp.end(), payload.begin(), payload.end());
            return emit(at_depth, tmp, holes, c.has_branch);
        }
        // branch over the distinct nibbles at `depth`
        Ref kids[16];
        bool present[16] = {};
        uint64_t i = lo;
        uint16_t state_mask = 0;
        bool all_hashed = true;
        while (i < hi) {
            uint8_t nb = nib(i, depth);
            uint64_t j = i;
            while (j < hi && nib(j, depth) == nb) j++;
            kids[nb] = build(i, j, depth + 1, at_depth + 1);
            if (err) return Ref{};
            present[nb] = true;
            state_mask |= uint16_t(1) << nb;
            if (kids[nb].slot == 0) all_hashed = false;
            i = j;
        }
        Ref r{};
        if (all_hashed) {
            Level& lv = levels[at_depth];
            int32_t row = int32_t(lv.masks.size());
            r.slot = next_slot++;
            lv.masks.push_back(state_mask);
            lv.bmp_slot.push_back(r.slot);
            for (int nb = 0; nb < 16; nb++)
                if (present[nb])
                    lv.children.push_back(Child{row, nb, kids[nb].slot});
        } else {
            std::vector<uint8_t> payload;
            std::vector<Hole> holes;
            for (int nb = 0; nb < 16; nb++) {
                if (!present[nb]) {
                    payload.push_back(0x80);
                    continue;
                }
                if (kids[nb].slot > 0) {
                    payload.push_back(0xA0);
                    holes.push_back(Hole{0, int32_t(payload.size()), kids[nb].slot});
                    payload.insert(payload.end(), 32, 0);
                } else {
                    payload.insert(payload.end(), scratch.begin() + kids[nb].inline_off,
                                   scratch.begin() + kids[nb].inline_off + kids[nb].inline_len);
                }
            }
            payload.push_back(0x80);  // empty branch value (secure trie)
            std::vector<uint8_t> tmp;
            list_header(tmp, payload.size());
            for (auto& h : holes) h.off += int32_t(tmp.size());
            tmp.insert(tmp.end(), payload.begin(), payload.end());
            r = emit(at_depth, tmp, holes, true);
        }
        r.has_branch = true;
        if (collect_meta) {
            BranchMeta m{};
            m.job = job;
            m.rep_key = uint32_t(lo);
            m.depth = uint16_t(at_depth);
            m.state_mask = state_mask;
            uint16_t tree = 0, hmask = 0;
            for (int nb = 0; nb < 16; nb++) {
                m.child_slot[nb] = -1;
                if (!present[nb]) continue;
                if (kids[nb].has_branch) tree |= uint16_t(1) << nb;
                if (kids[nb].slot > 0) {
                    hmask |= uint16_t(1) << nb;
                    m.child_slot[nb] = kids[nb].slot;
                }
            }
            m.tree_mask = tree;
            m.hash_mask = hmask;
            meta.push_back(m);
        }
        return r;
    }
};

struct Handle {
    std::vector<Level> levels;     // only non-empty, deepest first
    std::vector<uint32_t> depths;
    std::vector<int32_t> root_slot;      // per job; -1 => inline/empty
    std::vector<std::vector<uint8_t>> root_inline;
    std::vector<BranchMeta> meta;
    int32_t max_slot = 0;
};

}  // namespace

extern "C" {

// err: 0 ok, 1 unsorted keys, 2 duplicate keys, 3 bad input, 4 oversized
// value. start_depth: build each job's trie from nibble `start_depth` of
// its keys — the job's result is the SUBTRIE as it sits at that depth in
// the enclosing trie (leaf/ext paths are position-relative, so keys
// sharing a start_depth-nibble prefix yield exactly the embedded node).
// Chunked MerkleStage rebuilds commit per-prefix account subtries this
// way and stitch them as opaque boundaries (reth_tpu/stages/merkle.py).
void* rtb_build(const uint8_t* keys, uint64_t n_keys, const uint64_t* job_off,
                uint32_t n_jobs, const uint8_t* values, const uint64_t* val_off,
                int collect_meta, int start_depth, int* err) {
    *err = 0;
    if (!keys || !job_off || !values || !val_off || n_jobs == 0 ||
        start_depth < 0 || start_depth >= NIBS) {
        *err = 3;
        return nullptr;
    }
    Build b{};
    b.keys = keys;
    b.values = values;
    b.val_off = val_off;
    b.collect_meta = collect_meta != 0;
    auto h = new Handle();
    for (uint32_t j = 0; j < n_jobs; j++) {
        uint64_t lo = job_off[j], hi = job_off[j + 1];
        if (lo > hi || hi > n_keys) {
            *err = 3;
            delete h;
            return nullptr;
        }
        for (uint64_t i = lo + 1; i < hi; i++) {
            int c = memcmp(keys + (i - 1) * 32, keys + i * 32, 32);
            if (c >= 0) {
                *err = c == 0 ? 2 : 1;
                delete h;
                return nullptr;
            }
        }
        b.job = j;
        if (lo == hi) {
            h->root_slot.push_back(-1);
            h->root_inline.emplace_back();  // empty trie
            continue;
        }
        Ref r = b.build(lo, hi, start_depth, 0);
        if (b.err) {
            *err = b.err;
            delete h;
            return nullptr;
        }
        if (r.slot > 0) {
            h->root_slot.push_back(r.slot);
            h->root_inline.emplace_back();
        } else {
            h->root_slot.push_back(-1);
            h->root_inline.emplace_back(b.scratch.begin() + r.inline_off,
                                        b.scratch.begin() + r.inline_off + r.inline_len);
        }
    }
    for (int d = NIBS; d >= 0; d--) {
        Level& lv = b.levels[d];
        if (lv.row_slot.empty() && lv.masks.empty()) continue;
        h->levels.push_back(std::move(lv));
        h->depths.push_back(uint32_t(d));
    }
    h->meta = std::move(b.meta);
    h->max_slot = b.next_slot - 1;
    return h;
}

void rtb_free(void* hp) { delete static_cast<Handle*>(hp); }

int32_t rtb_num_levels(void* hp) {
    return int32_t(static_cast<Handle*>(hp)->levels.size());
}

int32_t rtb_max_slot(void* hp) { return static_cast<Handle*>(hp)->max_slot; }

uint32_t rtb_level_depth(void* hp, int32_t i) {
    return static_cast<Handle*>(hp)->depths[i];
}

// -- packed group -----------------------------------------------------------

uint64_t rtb_packed_bytes(void* hp, int32_t i) {
    return static_cast<Handle*>(hp)->levels[i].bytes.size();
}

uint32_t rtb_packed_rows(void* hp, int32_t i) {
    return uint32_t(static_cast<Handle*>(hp)->levels[i].row_slot.size());
}

uint32_t rtb_packed_holes(void* hp, int32_t i) {
    return uint32_t(static_cast<Handle*>(hp)->levels[i].holes.size());
}

void rtb_packed_get(void* hp, int32_t i, uint8_t* out_bytes, uint32_t* out_rowoff,
                    int32_t* out_slots) {
    Level& lv = static_cast<Handle*>(hp)->levels[i];
    memcpy(out_bytes, lv.bytes.data(), lv.bytes.size());
    memcpy(out_rowoff, lv.row_off.data(), lv.row_off.size() * 4);
    memcpy(out_slots, lv.row_slot.data(), lv.row_slot.size() * 4);
}

void rtb_packed_get_holes(void* hp, int32_t i, int32_t* row, int32_t* off,
                          int32_t* src) {
    Level& lv = static_cast<Handle*>(hp)->levels[i];
    for (size_t k = 0; k < lv.holes.size(); k++) {
        row[k] = lv.holes[k].row;
        off[k] = lv.holes[k].off;
        src[k] = lv.holes[k].src;
    }
}

// -- bitmap group -----------------------------------------------------------

uint32_t rtb_bmp_rows(void* hp, int32_t i) {
    return uint32_t(static_cast<Handle*>(hp)->levels[i].masks.size());
}

uint32_t rtb_bmp_children(void* hp, int32_t i) {
    return uint32_t(static_cast<Handle*>(hp)->levels[i].children.size());
}

void rtb_bmp_get(void* hp, int32_t i, uint16_t* masks, int32_t* slots) {
    Level& lv = static_cast<Handle*>(hp)->levels[i];
    memcpy(masks, lv.masks.data(), lv.masks.size() * 2);
    memcpy(slots, lv.bmp_slot.data(), lv.bmp_slot.size() * 4);
}

void rtb_bmp_get_children(void* hp, int32_t i, int32_t* row, int32_t* nb,
                          int32_t* src) {
    Level& lv = static_cast<Handle*>(hp)->levels[i];
    for (size_t k = 0; k < lv.children.size(); k++) {
        row[k] = lv.children[k].row;
        nb[k] = lv.children[k].nib;
        src[k] = lv.children[k].src;
    }
}

// -- roots ------------------------------------------------------------------

void rtb_roots(void* hp, int32_t* out) {
    Handle* h = static_cast<Handle*>(hp);
    memcpy(out, h->root_slot.data(), h->root_slot.size() * 4);
}

uint32_t rtb_root_inline_len(void* hp, uint32_t j) {
    return uint32_t(static_cast<Handle*>(hp)->root_inline[j].size());
}

void rtb_root_inline(void* hp, uint32_t j, uint8_t* out) {
    auto& v = static_cast<Handle*>(hp)->root_inline[j];
    memcpy(out, v.data(), v.size());
}

// -- branch meta (TrieUpdates) ---------------------------------------------

uint64_t rtb_meta_count(void* hp) {
    return static_cast<Handle*>(hp)->meta.size();
}

// packed per record: job u32, rep_key u32, depth u16, state u16, tree u16,
// hash u16, child_slot i32 x16  => 80 bytes
void rtb_meta_get(void* hp, uint8_t* out) {
    Handle* h = static_cast<Handle*>(hp);
    for (auto& m : h->meta) {
        memcpy(out, &m.job, 4); out += 4;
        memcpy(out, &m.rep_key, 4); out += 4;
        memcpy(out, &m.depth, 2); out += 2;
        memcpy(out, &m.state_mask, 2); out += 2;
        memcpy(out, &m.tree_mask, 2); out += 2;
        memcpy(out, &m.hash_mask, 2); out += 2;
        memcpy(out, m.child_slot, 64); out += 64;
    }
}

}  // extern "C"
