// Batched secp256k1 public-key recovery — the sender-recovery hot loop.
//
// Reference analogue: the C secp256k1 library + rayon batching behind
// SenderRecoveryStage (reference Cargo.toml:592,
// crates/stages/stages/src/stages/sender_recovery.rs). The pure-Python
// fallback (reth_tpu/primitives/secp256k1.py) is bit-exact but ~ms per
// signature; this implementation recovers Q = u1*G + u2*R with 4x64-limb
// field arithmetic (special-form reduction by p = 2^256 - 2^32 - 977) and
// an interleaved (Shamir) double scalar multiplication, threaded across
// the batch. The CALLER (Python) computes u1 = -z*r^-1 mod n and
// u2 = s*r^-1 mod n — big-int scalar math is microseconds in CPython and
// keeping mod-n arithmetic out of C++ halves the audit surface; parity
// with the Python implementation is pinned by tests/test_native_secp.py.
//
// Build: g++ -O3 -std=c++17 -shared -fPIC secp256k1.cpp -o libsecp.so

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

using u64 = uint64_t;
using u128 = unsigned __int128;

// field element: 4 x 64-bit little-endian limbs, value < p
struct Fe {
  u64 v[4];
};

constexpr u64 P0 = 0xFFFFFFFEFFFFFC2FULL;
constexpr u64 P1 = 0xFFFFFFFFFFFFFFFFULL;
constexpr u64 P2 = 0xFFFFFFFFFFFFFFFFULL;
constexpr u64 P3 = 0xFFFFFFFFFFFFFFFFULL;
constexpr u64 FOLD = 0x1000003D1ULL;  // 2^256 mod p

inline bool fe_gte_p(const Fe& a) {
  if (a.v[3] != P3) return a.v[3] > P3;
  if (a.v[2] != P2) return a.v[2] > P2;
  if (a.v[1] != P1) return a.v[1] > P1;
  return a.v[0] >= P0;
}

inline void fe_reduce_once(Fe& a) {
  if (!fe_gte_p(a)) return;
  // a -= p
  u64 borrow = 0;
  u64 limbs_p[4] = {P0, P1, P2, P3};
  for (int i = 0; i < 4; i++) {
    u128 t = (u128)a.v[i] - limbs_p[i] - borrow;
    a.v[i] = (u64)t;
    borrow = (t >> 64) ? 1 : 0;
  }
}

inline void fe_add(Fe& r, const Fe& a, const Fe& b) {
  u64 carry = 0;
  for (int i = 0; i < 4; i++) {
    u128 t = (u128)a.v[i] + b.v[i] + carry;
    r.v[i] = (u64)t;
    carry = (u64)(t >> 64);
  }
  if (carry) {  // fold 2^256 -> FOLD
    u128 t = (u128)r.v[0] + FOLD;
    r.v[0] = (u64)t;
    u64 c = (u64)(t >> 64);
    for (int i = 1; c && i < 4; i++) {
      t = (u128)r.v[i] + c;
      r.v[i] = (u64)t;
      c = (u64)(t >> 64);
    }
  }
  fe_reduce_once(r);
}

inline void fe_neg(Fe& r, const Fe& a) {
  // r = p - a (a < p)
  u64 limbs_p[4] = {P0, P1, P2, P3};
  u64 borrow = 0;
  for (int i = 0; i < 4; i++) {
    u128 t = (u128)limbs_p[i] - a.v[i] - borrow;
    r.v[i] = (u64)t;
    borrow = (t >> 64) ? 1 : 0;
  }
  // a == 0 -> r == p: reduce
  fe_reduce_once(r);
}

inline void fe_sub(Fe& r, const Fe& a, const Fe& b) {
  Fe nb;
  fe_neg(nb, b);
  fe_add(r, a, nb);
}

// full 256x256 -> 512 multiply, then reduce mod p via 2^256 == FOLD
inline void fe_mul(Fe& r, const Fe& a, const Fe& b) {
  u64 lo[4] = {0, 0, 0, 0}, hi[4] = {0, 0, 0, 0};
  for (int i = 0; i < 4; i++) {
    u64 carry = 0;
    for (int j = 0; j < 4; j++) {
      int k = i + j;
      u128 cur = (u128)a.v[i] * b.v[j] + carry;
      u128 acc = (k < 4 ? (u128)lo[k] : (u128)hi[k - 4]) + (u64)cur;
      if (k < 4) lo[k] = (u64)acc;
      else hi[k - 4] = (u64)acc;
      carry = (u64)(cur >> 64) + (u64)(acc >> 64);
    }
    int k = i + 4;
    while (carry) {
      u128 acc = (u128)(k < 4 ? lo[k] : hi[k - 4]) + carry;
      if (k < 4) lo[k] = (u64)acc;
      else hi[k - 4] = (u64)acc;
      carry = (u64)(acc >> 64);
      k++;
    }
  }
  // fold: result = lo + hi * FOLD  (hi * FOLD fits in 4 limbs + small carry)
  u64 carry = 0;
  u64 mid[5] = {0, 0, 0, 0, 0};
  for (int i = 0; i < 4; i++) {
    u128 t = (u128)hi[i] * FOLD + mid[i] + carry;
    mid[i] = (u64)t;
    carry = (u64)(t >> 64);
  }
  mid[4] = carry;
  Fe res;
  carry = 0;
  for (int i = 0; i < 4; i++) {
    u128 t = (u128)lo[i] + mid[i] + carry;
    res.v[i] = (u64)t;
    carry = (u64)(t >> 64);
  }
  u64 over = carry + mid[4];  // multiples of 2^256 still to fold
  while (over) {
    u128 t = (u128)res.v[0] + (u128)over * FOLD;
    res.v[0] = (u64)t;
    u64 c = (u64)(t >> 64);
    for (int i = 1; c && i < 4; i++) {
      t = (u128)res.v[i] + c;
      res.v[i] = (u64)t;
      c = (u64)(t >> 64);
    }
    over = c;
  }
  fe_reduce_once(res);
  r = res;
}

inline void fe_sqr(Fe& r, const Fe& a) { fe_mul(r, a, a); }

inline bool fe_is_zero(const Fe& a) {
  return (a.v[0] | a.v[1] | a.v[2] | a.v[3]) == 0;
}

inline bool fe_eq(const Fe& a, const Fe& b) {
  return a.v[0] == b.v[0] && a.v[1] == b.v[1] && a.v[2] == b.v[2] &&
         a.v[3] == b.v[3];
}

void fe_pow(Fe& r, const Fe& a, const u64 e[4]) {
  Fe base = a;
  Fe acc{{1, 0, 0, 0}};
  for (int limb = 0; limb < 4; limb++) {
    u64 bits = e[limb];
    for (int i = 0; i < 64; i++) {
      if (bits & 1) fe_mul(acc, acc, base);
      fe_sqr(base, base);
      bits >>= 1;
    }
  }
  r = acc;
}

void fe_inv(Fe& r, const Fe& a) {
  // Fermat: a^(p-2)
  const u64 e[4] = {P0 - 2, P1, P2, P3};
  fe_pow(r, a, e);
}

bool fe_sqrt(Fe& r, const Fe& a) {
  // p % 4 == 3: sqrt = a^((p+1)/4)
  const u64 e[4] = {0xFFFFFFFFBFFFFF0CULL, 0xFFFFFFFFFFFFFFFFULL,
                    0xFFFFFFFFFFFFFFFFULL, 0x3FFFFFFFFFFFFFFFULL};
  fe_pow(r, a, e);
  Fe chk;
  fe_sqr(chk, r);
  return fe_eq(chk, a);
}

void fe_from_bytes(Fe& r, const uint8_t* be32) {
  for (int i = 0; i < 4; i++) {
    u64 v = 0;
    for (int j = 0; j < 8; j++) v = (v << 8) | be32[(3 - i) * 8 + j];
    r.v[i] = v;
  }
}

void fe_to_bytes(uint8_t* be32, const Fe& a) {
  for (int i = 0; i < 4; i++) {
    u64 v = a.v[3 - i];
    for (int j = 0; j < 8; j++) be32[i * 8 + j] = (uint8_t)(v >> (56 - 8 * j));
  }
}

// -- Jacobian points ---------------------------------------------------------

struct Jac {
  Fe x, y, z;
  bool inf;
};

const Fe FE_SEVEN{{7, 0, 0, 0}};

void jac_double(Jac& r, const Jac& p) {
  if (p.inf || fe_is_zero(p.y)) {
    r.inf = true;
    return;
  }
  Fe ysq, s, m, t, x3, y3, z3;
  fe_sqr(ysq, p.y);
  fe_mul(s, p.x, ysq);
  fe_add(s, s, s);
  fe_add(s, s, s);              // s = 4 x y^2
  Fe xsq;
  fe_sqr(xsq, p.x);
  fe_add(m, xsq, xsq);
  fe_add(m, m, xsq);            // m = 3 x^2  (a = 0)
  fe_sqr(x3, m);
  fe_sub(x3, x3, s);
  fe_sub(x3, x3, s);            // x3 = m^2 - 2 s
  Fe ysq2;
  fe_sqr(ysq2, ysq);
  fe_add(ysq2, ysq2, ysq2);
  fe_add(ysq2, ysq2, ysq2);
  fe_add(ysq2, ysq2, ysq2);     // 8 y^4
  fe_sub(t, s, x3);
  fe_mul(y3, m, t);
  fe_sub(y3, y3, ysq2);         // y3 = m (s - x3) - 8 y^4
  fe_mul(z3, p.y, p.z);
  fe_add(z3, z3, z3);           // z3 = 2 y z
  r.x = x3;
  r.y = y3;
  r.z = z3;
  r.inf = false;
}

void jac_add(Jac& r, const Jac& p, const Jac& q) {
  if (p.inf) { r = q; return; }
  if (q.inf) { r = p; return; }
  Fe z1sq, z2sq, u1, u2, s1, s2;
  fe_sqr(z1sq, p.z);
  fe_sqr(z2sq, q.z);
  fe_mul(u1, p.x, z2sq);
  fe_mul(u2, q.x, z1sq);
  Fe z2cu, z1cu;
  fe_mul(z2cu, z2sq, q.z);
  fe_mul(z1cu, z1sq, p.z);
  fe_mul(s1, p.y, z2cu);
  fe_mul(s2, q.y, z1cu);
  if (fe_eq(u1, u2)) {
    if (fe_eq(s1, s2)) {
      jac_double(r, p);
      return;
    }
    r.inf = true;
    return;
  }
  Fe h, rr, hsq, hcu, u1hsq;
  fe_sub(h, u2, u1);
  fe_sub(rr, s2, s1);
  fe_sqr(hsq, h);
  fe_mul(hcu, hsq, h);
  fe_mul(u1hsq, u1, hsq);
  Fe x3, y3, z3, t;
  fe_sqr(x3, rr);
  fe_sub(x3, x3, hcu);
  fe_sub(x3, x3, u1hsq);
  fe_sub(x3, x3, u1hsq);        // x3 = r^2 - h^3 - 2 u1 h^2
  fe_sub(t, u1hsq, x3);
  fe_mul(y3, rr, t);
  Fe s1hcu;
  fe_mul(s1hcu, s1, hcu);
  fe_sub(y3, y3, s1hcu);        // y3 = r (u1 h^2 - x3) - s1 h^3
  fe_mul(z3, p.z, q.z);
  fe_mul(z3, z3, h);            // z3 = z1 z2 h
  r.x = x3;
  r.y = y3;
  r.z = z3;
  r.inf = false;
}

// generator
const uint8_t GX_BE[32] = {
    0x79, 0xBE, 0x66, 0x7E, 0xF9, 0xDC, 0xBB, 0xAC, 0x55, 0xA0, 0x62, 0x95,
    0xCE, 0x87, 0x0B, 0x07, 0x02, 0x9B, 0xFC, 0xDB, 0x2D, 0xCE, 0x28, 0xD9,
    0x59, 0xF2, 0x81, 0x5B, 0x16, 0xF8, 0x17, 0x98};
const uint8_t GY_BE[32] = {
    0x48, 0x3A, 0xDA, 0x77, 0x26, 0xA3, 0xC4, 0x65, 0x5D, 0xA4, 0xFB, 0xFC,
    0x0E, 0x11, 0x08, 0xA8, 0xFD, 0x17, 0xB4, 0x48, 0xA6, 0x85, 0x54, 0x19,
    0x9C, 0x47, 0xD0, 0x8F, 0xFB, 0x10, 0xD4, 0xB8};

// Interleaved double-scalar multiplication: k1*A + k2*B (Shamir's trick).
// Scalars as 32-byte big-endian.
void dual_mul(Jac& out, const uint8_t* k1, const Jac& a, const uint8_t* k2,
              const Jac& b) {
  Jac sum_ab;
  jac_add(sum_ab, a, b);
  Jac acc;
  acc.inf = true;
  for (int byte = 0; byte < 32; byte++) {
    for (int bit = 7; bit >= 0; bit--) {
      jac_double(acc, acc);
      bool b1 = (k1[byte] >> bit) & 1;
      bool b2 = (k2[byte] >> bit) & 1;
      if (b1 && b2) jac_add(acc, acc, sum_ab);
      else if (b1) jac_add(acc, acc, a);
      else if (b2) jac_add(acc, acc, b);
    }
  }
  out = acc;
}

// recover one pubkey; returns 0 ok, nonzero error
int recover_one(const uint8_t* r_be, uint8_t parity, const uint8_t* u1,
                const uint8_t* u2, uint8_t* out64) {
  Fe x;
  fe_from_bytes(x, r_be);
  Fe rhs, xsq;
  fe_sqr(xsq, x);
  fe_mul(rhs, xsq, x);
  fe_add(rhs, rhs, FE_SEVEN);
  Fe y;
  if (!fe_sqrt(y, rhs)) return 1;  // x not on curve
  if ((y.v[0] & 1) != (parity & 1)) fe_neg(y, y);
  Jac g;
  fe_from_bytes(g.x, GX_BE);
  fe_from_bytes(g.y, GY_BE);
  g.z = Fe{{1, 0, 0, 0}};
  g.inf = false;
  Jac rp{x, y, Fe{{1, 0, 0, 0}}, false};
  Jac q;
  dual_mul(q, u1, g, u2, rp);
  if (q.inf) return 2;
  // to affine
  Fe zinv, zinv2, zinv3, ax, ay;
  fe_inv(zinv, q.z);
  fe_sqr(zinv2, zinv);
  fe_mul(zinv3, zinv2, zinv);
  fe_mul(ax, q.x, zinv2);
  fe_mul(ay, q.y, zinv3);
  fe_to_bytes(out64, ax);
  fe_to_bytes(out64 + 32, ay);
  return 0;
}

}  // namespace

extern "C" {

// Batch recovery. Arrays of n elements:
//   r:      n x 32 bytes (big-endian signature r; the R point's x)
//   parity: n bytes (recovery bit)
//   u1/u2:  n x 32 bytes big-endian (caller-computed -z*r^-1, s*r^-1 mod n)
//   out:    n x 64 bytes (X||Y)
//   status: n bytes (0 ok, nonzero = unrecoverable)
// n_threads <= 0 picks the hardware concurrency.
void rtsecp_recover_batch(const uint8_t* r, const uint8_t* parity,
                          const uint8_t* u1, const uint8_t* u2, uint64_t n,
                          uint8_t* out, uint8_t* status, int n_threads) {
  if (n == 0) return;
  unsigned workers = n_threads > 0
                         ? (unsigned)n_threads
                         : std::max(1u, std::thread::hardware_concurrency());
  if (workers > n) workers = (unsigned)n;
  auto work = [&](uint64_t lo, uint64_t hi) {
    for (uint64_t i = lo; i < hi; i++) {
      status[i] = (uint8_t)recover_one(r + 32 * i, parity[i], u1 + 32 * i,
                                       u2 + 32 * i, out + 64 * i);
    }
  };
  if (workers == 1) {
    work(0, n);
    return;
  }
  std::vector<std::thread> threads;
  uint64_t chunk = (n + workers - 1) / workers;
  for (unsigned w = 0; w < workers; w++) {
    uint64_t lo = w * chunk;
    uint64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    threads.emplace_back(work, lo, hi);
  }
  for (auto& t : threads) t.join();
}

}  // extern "C"
